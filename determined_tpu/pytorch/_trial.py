"""PyTorchTrial / PyTorchTrialContext / Trainer.

Reference mapping (harness/determined/pytorch/):
  - PyTorchTrial user overrides        _pytorch_trial.py:1391-1568
  - _PyTorchTrialController.run        _pytorch_trial.py:548 (op loop :736,
    hot loop :681, train step :861, validate :916, checkpoint :384)
  - PyTorchTrialContext wrap_model/
    wrap_optimizer/backward/step       _pytorch_context.py:285-297,1054
  - Trainer.fit                        _trainer.py:70 (backend init :206-228)

Device selection: torch_xla if importable (TPU task env), else cpu/cuda.
Gradient aggregation/mixed precision hooks are kept minimal — on TPU the
performant path is the JAX trial; this API is for porting torch codebases
onto the platform without rewrites.
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Any, Dict, Iterator, List, Optional, Union

import torch

from determined_tpu import core
from determined_tpu.core._distributed import DistributedContext

logger = logging.getLogger("determined_tpu.pytorch")

TorchData = Union[Dict[str, torch.Tensor], List[torch.Tensor], torch.Tensor]


def _default_device() -> torch.device:
    try:  # torch-xla present in TPU task environments
        import torch_xla.core.xla_model as xm  # type: ignore

        return xm.xla_device()
    except ImportError:
        return torch.device("cuda" if torch.cuda.is_available() else "cpu")


class TorchDistTransport:
    """Byte-level control-plane collectives over torch.distributed — the
    torch compat trials' analogue of the jax multihost transport
    (core/_distributed.py), so one DistributedContext implementation serves
    both runtimes."""

    def allgather_bytes(self, payload: bytes) -> List[bytes]:
        import torch.distributed as dist

        out: List[Optional[bytes]] = [None] * dist.get_world_size()
        dist.all_gather_object(out, payload)
        return out  # type: ignore[return-value]

    def broadcast_bytes(self, payload: bytes, is_source: bool) -> bytes:
        import torch.distributed as dist

        box: List[Optional[bytes]] = [payload if is_source else None]
        dist.broadcast_object_list(box, src=0)
        assert box[0] is not None
        return box[0]

    def barrier(self, name: str) -> None:
        import torch.distributed as dist

        dist.barrier()


def init_torch_distributed() -> Optional[DistributedContext]:
    """Bring up torch.distributed from the launch layer's env contract
    (determined_tpu/launch/torch_distributed.py): RANK/WORLD_SIZE/
    MASTER_ADDR(+PORT)/DET_TORCH_BACKEND. Returns None when not launched
    distributed. Reference: pytorch/_trainer.py:206-228 backend init.

    Backends: `xla` (torch-xla on TPU task environments, xla:// init —
    one process per host owning all local chips), `gloo` (CPU), `nccl`.
    """
    world = int(os.environ.get("WORLD_SIZE", "1"))
    if world <= 1:
        return None
    import torch.distributed as dist

    backend = os.environ.get("DET_TORCH_BACKEND", "")
    if not backend:
        backend = "nccl" if torch.cuda.is_available() else "gloo"
    if not dist.is_initialized():
        if backend == "xla":
            dist.init_process_group("xla", init_method="xla://")
        else:
            dist.init_process_group(backend, init_method="env://")
    return DistributedContext(
        rank=dist.get_rank(),
        size=dist.get_world_size(),
        transport=TorchDistTransport(),
    )


def _is_fsdp(model: torch.nn.Module) -> bool:
    # torch-xla's XlaFullyShardedDataParallel / torch's FSDP — matched by
    # name so the check works without torch_xla installed.
    return any(
        "FullyShardedDataParallel" in type(m).__name__ for m in
        (model, getattr(model, "module", model))
    )


def _unwrap(model: torch.nn.Module) -> torch.nn.Module:
    if isinstance(model, torch.nn.parallel.DistributedDataParallel):
        return model.module
    return model


class DataLoader:
    """Thin wrapper mirroring determined.pytorch.DataLoader (pytorch/_data.py):
    records constructor args so the controller can apply per-worker sharding
    (reference samplers.py) before building the real torch DataLoader."""

    def __init__(self, dataset, batch_size: int = 1, shuffle: bool = False,
                 **kwargs: Any):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.kwargs = kwargs

    def get_data_loader(self, num_replicas: int = 1, rank: int = 0):
        sampler = None
        shuffle = self.shuffle
        if num_replicas > 1:
            sampler = torch.utils.data.distributed.DistributedSampler(
                self.dataset, num_replicas=num_replicas, rank=rank,
                shuffle=self.shuffle,
            )
            shuffle = False
        return torch.utils.data.DataLoader(
            self.dataset, batch_size=self.batch_size, shuffle=shuffle,
            sampler=sampler, **self.kwargs,
        )


class PyTorchTrialContext:
    """Services exposed to the user trial (reference _pytorch_context.py)."""

    def __init__(self, core_context: Optional[core.Context] = None,
                 hparams: Optional[Dict[str, Any]] = None,
                 device: Optional[torch.device] = None):
        # Process group FIRST (before any wrap_model): construction order is
        # context → trial(__init__ wraps models) → Trainer.
        self.dist = init_torch_distributed()
        self._core = core_context
        self._hparams = hparams or (core_context.hparams if core_context else {})
        self.device = device or _default_device()
        self.models: List[torch.nn.Module] = []
        self.optimizers: List[torch.optim.Optimizer] = []
        self.lr_schedulers: List[Any] = []
        self._epoch_len: Optional[int] = None

    # -- user surface --------------------------------------------------
    def get_hparam(self, name: str) -> Any:
        if name not in self._hparams:
            raise KeyError(f"hparam {name!r} not set")
        return self._hparams[name]

    def get_hparams(self) -> Dict[str, Any]:
        return dict(self._hparams)

    def wrap_model(self, model: torch.nn.Module) -> torch.nn.Module:
        """Move to device; wrap in DistributedDataParallel when launched
        distributed (reference _pytorch_context.py:297). torch-xla supports
        DDP over the xla backend, so the wrap is uniform."""
        model = model.to(self.device)
        if self.dist is not None and self.dist.size > 1 and not _is_fsdp(model):
            # FSDP-wrapped models already own their gradient comms — DDP on
            # top would all-reduce reduce-scattered shards (wrong grads).
            device_ids = (
                [self.device] if self.device.type == "cuda" else None
            )
            model = torch.nn.parallel.DistributedDataParallel(
                model, device_ids=device_ids
            )
        self.models.append(model)
        return model

    def wrap_optimizer(self, optimizer: torch.optim.Optimizer) -> torch.optim.Optimizer:
        self.optimizers.append(optimizer)
        return optimizer

    def wrap_lr_scheduler(self, scheduler: Any) -> Any:
        self.lr_schedulers.append(scheduler)
        return scheduler

    def backward(self, loss: torch.Tensor) -> None:
        loss.backward()

    def step_optimizer(self, optimizer: torch.optim.Optimizer) -> None:
        optimizer.step()
        optimizer.zero_grad(set_to_none=True)
        try:
            import torch_xla.core.xla_model as xm  # type: ignore

            xm.mark_step()
        except ImportError:
            pass

    def to_device(self, data: TorchData) -> TorchData:
        if isinstance(data, dict):
            return {k: self.to_device(v) for k, v in data.items()}
        if isinstance(data, (list, tuple)):
            return type(data)(self.to_device(v) for v in data)
        if isinstance(data, torch.Tensor):
            return data.to(self.device)
        return data

    @property
    def distributed(self):
        return self._core.distributed if self._core else None


class PyTorchTrial:
    """User subclass surface (reference _pytorch_trial.py:1391)."""

    def __init__(self, context: PyTorchTrialContext):
        self.context = context

    def train_batch(self, batch: TorchData, epoch_idx: int,
                    batch_idx: int) -> Dict[str, Any]:
        raise NotImplementedError

    def evaluate_batch(self, batch: TorchData,
                       batch_idx: int) -> Dict[str, Any]:
        raise NotImplementedError

    def build_training_data_loader(self) -> DataLoader:
        raise NotImplementedError

    def build_validation_data_loader(self) -> DataLoader:
        raise NotImplementedError

    # Optional checkpoint hooks (reference save/load in the controller).
    def state_dict_extras(self) -> Dict[str, Any]:
        return {}

    def load_state_dict_extras(self, extras: Dict[str, Any]) -> None:
        pass


class Trainer:
    """Controller + Trainer.fit (reference _trainer.py:70 +
    _PyTorchTrialController.run :548)."""

    def __init__(self, trial: PyTorchTrial,
                 core_context: Optional[core.Context] = None):
        self.trial = trial
        self.context = trial.context
        self.dist = self.context.dist
        self.core = core_context or self.context._core or core.init(
            max_length=100, distributed=self.dist
        )
        if (
            self.dist is not None
            and self.core.distributed.size != self.dist.size
        ):
            # A core context that doesn't know the torch process group would
            # make every rank act as chief (N-fold op completions/reports).
            raise ValueError(
                f"core context distributed size "
                f"{self.core.distributed.size} != torch world size "
                f"{self.dist.size}; build it with "
                "core.init(distributed=trial.context.dist)"
            )

    @property
    def _world(self) -> int:
        return self.dist.size if self.dist is not None else 1

    @property
    def _rank(self) -> int:
        return self.dist.rank if self.dist is not None else 0

    # -- checkpointing -------------------------------------------------
    def _sharded_models(self) -> bool:
        return any(_is_fsdp(m) for m in self.context.models)

    def _state(self, steps_completed: int) -> Dict[str, Any]:
        return {
            "models": [_unwrap(m).state_dict() for m in self.context.models],
            "optimizers": [o.state_dict() for o in self.context.optimizers],
            "steps_completed": steps_completed,
            "extras": self.trial.state_dict_extras(),
        }

    def _save(self, steps_completed: int) -> None:
        if self._sharded_models() and self._world > 1:
            # FSDP: each rank's state_dict holds only ITS shard — every rank
            # uploads state-rank{r}.pt into one storage id (sharded upload,
            # reference core/_checkpoint.py:282 semantics).
            import tempfile

            with tempfile.TemporaryDirectory() as td:
                torch.save(self._state(steps_completed),
                           os.path.join(td, f"state-rank{self._rank}.pt"))
                self.core.checkpoint.upload(
                    td,
                    metadata={"steps_completed": steps_completed,
                              "framework": "pytorch", "sharded": True,
                              "world_size": self._world},
                    shard=True,
                )
            return
        if self.dist is not None and not self.dist.is_chief:
            self.dist.barrier("ckpt")  # chief writes; workers wait
            return
        with self.core.checkpoint.store_path(
            {"steps_completed": steps_completed, "framework": "pytorch"}
        ) as (path, _sid):
            torch.save(self._state(steps_completed), f"{path}/state.pt")
        if self.dist is not None:
            self.dist.barrier("ckpt")

    def _restore(self) -> int:
        latest = self.core.latest_checkpoint
        if not latest:
            return 0
        with self.core.checkpoint.restore_path(latest) as path:
            sharded = os.path.join(path, f"state-rank{self._rank}.pt")
            fname = sharded if os.path.exists(sharded) else f"{path}/state.pt"
            if not os.path.exists(fname):
                raise FileNotFoundError(
                    f"checkpoint {latest}: no {os.path.basename(sharded)} or "
                    "state.pt — resuming a sharded checkpoint needs the same "
                    "world size it was saved with"
                )
            state = torch.load(fname, map_location=self.context.device,
                               weights_only=False)
        for model, sd in zip(self.context.models, state["models"]):
            _unwrap(model).load_state_dict(sd)
        for opt, sd in zip(self.context.optimizers, state["optimizers"]):
            opt.load_state_dict(sd)
        self.trial.load_state_dict_extras(state.get("extras", {}))
        logger.info("restored at step %d", state["steps_completed"])
        return int(state["steps_completed"])

    def _validate(self, steps_completed: int) -> Dict[str, Any]:
        # Each rank evaluates its shard; sums are reduced over the control
        # plane (reference: distributed metric reducers, pytorch/_reducer.py).
        loader = self.trial.build_validation_data_loader().get_data_loader(
            num_replicas=self._world, rank=self._rank
        )
        for model in self.context.models:
            model.eval()
        totals: Dict[str, float] = {}
        n = 0
        with torch.no_grad():
            for batch_idx, batch in enumerate(loader):
                batch = self.context.to_device(batch)
                metrics = self.trial.evaluate_batch(batch, batch_idx)
                for k, v in metrics.items():
                    totals[k] = totals.get(k, 0.0) + float(v)
                n += 1
        for model in self.context.models:
            model.train()
        if self.dist is not None and self.dist.size > 1:
            parts = self.dist.allgather((totals, n))
            totals, n = {}, 0
            for t, c in parts:
                n += c
                for k, v in t.items():
                    totals[k] = totals.get(k, 0.0) + v
        reduced = {k: v / max(n, 1) for k, v in totals.items()}
        self.core.train.report_validation_metrics(steps_completed, reduced)
        return reduced

    def fit(
        self,
        validation_period: int = 0,  # batches; 0 = only at op boundaries
        checkpoint_period: int = 0,
        searcher_metric: Optional[str] = None,
        report_period: int = 10,
    ) -> int:
        """Run the searcher-driven train/validate/checkpoint loop; returns
        total batches trained."""
        steps = self._restore()
        epoch_idx = 0
        data_iter: Optional[Iterator] = None

        def next_batch():
            nonlocal data_iter, epoch_idx
            while True:
                if data_iter is None:
                    dl = self.trial.build_training_data_loader().get_data_loader(
                        num_replicas=self._world, rank=self._rank
                    )
                    data_iter = iter(dl)
                try:
                    return next(data_iter)
                except StopIteration:
                    data_iter = None
                    epoch_idx += 1

        window: Dict[str, float] = {}
        window_n = 0
        for op in self.core.searcher.operations():
            while steps < op.length:
                batch = self.context.to_device(next_batch())
                metrics = self.trial.train_batch(batch, epoch_idx, steps)
                steps += 1
                for k, v in metrics.items():
                    try:
                        window[k] = window.get(k, 0.0) + float(v)
                    except (TypeError, ValueError):
                        continue
                window_n += 1
                if steps % report_period == 0 or steps == op.length:
                    self.core.train.report_training_metrics(
                        steps, {k: v / window_n for k, v in window.items()}
                    )
                    window, window_n = {}, 0
                if validation_period and steps % validation_period == 0:
                    self._validate(steps)
                if checkpoint_period and steps % checkpoint_period == 0:
                    self._save(steps)
                if self.core.preempt.should_preempt():
                    self._save(steps)
                    logger.info("preempted at step %d", steps)
                    return steps
            val_metrics = self._validate(steps)
            metric_name = searcher_metric or (
                self.core.info.trial.config.get("searcher", {}).get("metric")
                if self.core.info and self.core.info.trial else None
            )
            if metric_name is not None and metric_name not in val_metrics:
                # Reporting an arbitrary substitute would corrupt ASHA
                # promotion ordering; fail loudly like keras/_trial.py and
                # the reference do.
                raise KeyError(
                    f"searcher metric {metric_name!r} not in validation "
                    f"metrics {sorted(val_metrics)}"
                )
            metric_value = (
                val_metrics[metric_name]
                if metric_name is not None
                else next(iter(val_metrics.values()), 0.0)
            )
            op.report_completed(float(metric_value))
            self._save(steps)
        return steps
