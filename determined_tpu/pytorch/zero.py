"""ZeRO-1 engine: a real, deepspeed-engine-shaped optimizer-state-sharding
runtime for DeepSpeedTrial subclasses.

Reference semantics: deepspeed ZeRO stage 1 as used by
`examples/deepspeed/gpt_neox/zero1.yaml` (reference
harness/determined/pytorch/deepspeed/_deepspeed_trial.py drives the engine;
the engine itself lives in the deepspeed library). The TPU-native design
maps the partitioned update onto torch.distributed collectives, which the
launch layer binds to gloo on CPU hosts and to the `xla://` backend on
torch-xla task images — where each collective lowers to an XLA ICI
collective, the same transport the JAX FSDP path uses:

  - gradients are averaged with one flat-bucket all_reduce
    (ring all-reduce over ICI on TPU);
  - each data-parallel rank owns a contiguous slice of the parameter list
    (balanced by numel) and keeps optimizer state ONLY for that slice —
    optimizer memory per chip drops ~1/world;
  - after the owner applies its slice's update, updated parameters are
    rebroadcast from their owners (the all-gather leg of ZeRO-1).

Checkpoints are engine-sharded like deepspeed's: every rank writes its own
optimizer-state shard; the full module state is written by rank 0 only.
`DeepSpeedTrainer._save` uploads with `shard=True`, so all shards land in
one platform checkpoint.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import torch

logger = logging.getLogger("determined_tpu.pytorch.zero")


def _dist():
    """The process group the launch layer initialised, or None single-proc."""
    if torch.distributed.is_available() and torch.distributed.is_initialized():
        return torch.distributed
    return None


def _partition(params: List[torch.nn.Parameter], world: int) -> List[int]:
    """Greedy balanced assignment of params to ranks by numel; returns
    owner rank per param (ownership may interleave). The guarantee the
    collectives rely on is determinism: all ranks iterate the same module
    in the same order, so every rank computes the same assignment."""
    owners = [0] * len(params)
    loads = [0] * world
    # Stable greedy: walk params in order, give each to the lightest rank.
    # All ranks iterate the same module in the same order → same answer.
    for i, p in enumerate(params):
        r = loads.index(min(loads))
        owners[i] = r
        loads[r] += p.numel()
    return owners


class ZeroOneEngine:
    """Deepspeed-engine contract (train_micro_batch_size_per_gpu /
    gradient_accumulation_steps / __call__ / backward / step /
    save_checkpoint / load_checkpoint) with ZeRO-1 partitioned optimizer
    semantics over torch.distributed."""

    def __init__(
        self,
        model: torch.nn.Module,
        optimizer_factory: Callable[[Iterable[torch.nn.Parameter]],
                                    torch.optim.Optimizer],
        *,
        micro_batch_size: int,
        gradient_accumulation: int = 1,
    ):
        self.module = model
        self._micro_bs = int(micro_batch_size)
        self._grad_accum = max(1, int(gradient_accumulation))
        self._micro_steps = 0

        dist = _dist()
        self._world = dist.get_world_size() if dist else 1
        self._rank = dist.get_rank() if dist else 0
        self._params = [p for p in model.parameters() if p.requires_grad]
        self._owners = _partition(self._params, self._world)
        owned = [p for p, o in zip(self._params, self._owners)
                 if o == self._rank]
        # The optimizer only ever sees this rank's slice — that IS the
        # ZeRO-1 memory saving (state for ~1/world of the params).
        self.optimizer = optimizer_factory(owned if owned else
                                           [torch.nn.Parameter(torch.zeros(1))])
        self._owned = owned
        if self._world > 1:
            logger.info(
                "zero1: rank %d/%d owns %d/%d params (%d elems)",
                self._rank, self._world, len(owned), len(self._params),
                sum(p.numel() for p in owned))

    # -- deepspeed contract -------------------------------------------
    def train_micro_batch_size_per_gpu(self) -> int:
        return self._micro_bs

    def gradient_accumulation_steps(self) -> int:
        return self._grad_accum

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.module(*args, **kwargs)

    def backward(self, loss: torch.Tensor) -> None:
        (loss / self._grad_accum).backward()

    def step(self) -> None:
        """Advance one microbatch; at the accumulation boundary run the
        partitioned update (all_reduce grads → owner step → rebroadcast)."""
        self._micro_steps += 1
        if self._micro_steps % self._grad_accum != 0:
            return
        dist = _dist()
        if dist is not None and self._world > 1:
            self._allreduce_grads(dist)
        self.optimizer.step()
        for p in self._params:
            p.grad = None
        if dist is not None and self._world > 1:
            self._rebroadcast_params(dist)

    def _allreduce_grads(self, dist) -> None:
        """Flat-bucket gradient averaging: one collective per ~32MB bucket
        instead of one per tensor (launch latency dominates small
        collectives on both gloo and ICI). Buckets group by (dtype,
        device) — mixed-precision models carry bf16 and fp32 grads and
        torch.cat refuses to mix them."""
        LIMIT = 32 << 20
        buckets: Dict[Any, List[torch.Tensor]] = {}
        sizes: Dict[Any, int] = {}

        def flush(key: Any) -> None:
            bucket = buckets.pop(key, [])
            sizes.pop(key, 0)
            if not bucket:
                return
            flat = torch.cat([g.reshape(-1) for g in bucket])
            dist.all_reduce(flat)
            flat /= self._world
            off = 0
            for g in bucket:
                g.copy_(flat[off:off + g.numel()].view_as(g))
                off += g.numel()

        for p in self._params:
            if p.grad is None:
                p.grad = torch.zeros_like(p)
            key = (p.grad.dtype, p.grad.device)
            buckets.setdefault(key, []).append(p.grad)
            sizes[key] = sizes.get(key, 0) + \
                p.grad.numel() * p.grad.element_size()
            if sizes[key] >= LIMIT:
                flush(key)
        for key in list(buckets):
            flush(key)

    def _rebroadcast_params(self, dist) -> None:
        """The all-gather leg of ZeRO-1: owners publish their updated
        params. Flat-bucketed per (owner, dtype, device) for the same
        launch-latency reason as the gradient path — one broadcast per
        parameter would dominate step time on a 290-tensor model.
        Buckets flush at the same ~32MB cap as _allreduce_grads: an
        uncapped torch.cat materializes a contiguous copy of ~1/world of
        ALL parameters per bucket every optimizer step (plus the
        copy-back), a transient spike of hundreds of MB at larger
        configs. Flush order is deterministic and identical on all ranks
        (same module walk, same sizes), which the collectives require."""
        LIMIT = 32 << 20
        with torch.no_grad():
            buckets: Dict[Any, List[torch.nn.Parameter]] = {}
            sizes: Dict[Any, int] = {}

            def flush(key: Any) -> None:
                ps = buckets.pop(key, [])
                sizes.pop(key, 0)
                if not ps:
                    return
                flat = torch.cat([p.data.reshape(-1) for p in ps])
                dist.broadcast(flat, src=key[0])
                off = 0
                for p in ps:
                    p.data.copy_(flat[off:off + p.numel()].view_as(p))
                    off += p.numel()

            for p, owner in zip(self._params, self._owners):
                key = (owner, p.dtype, p.device)
                buckets.setdefault(key, []).append(p)
                sizes[key] = sizes.get(key, 0) + p.numel() * p.element_size()
                if sizes[key] >= LIMIT:
                    flush(key)
            for key in sorted(list(buckets), key=str):
                flush(key)

    # -- engine-sharded checkpoints -----------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None) -> None:
        tag = tag or "zero1"
        os.makedirs(save_dir, exist_ok=True)
        if self._rank == 0:
            torch.save(self.module.state_dict(),
                       os.path.join(save_dir, f"{tag}-model.pt"))
        torch.save(
            {"optimizer": self.optimizer.state_dict(),
             "world": self._world, "rank": self._rank},
            os.path.join(save_dir, f"{tag}-opt-rank{self._rank}.pt"))

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None) -> None:
        tag = tag or "zero1"
        model_path = os.path.join(load_dir, f"{tag}-model.pt")
        self.module.load_state_dict(
            torch.load(model_path, weights_only=False))
        shard = os.path.join(load_dir, f"{tag}-opt-rank{self._rank}.pt")
        if os.path.exists(shard):
            state = torch.load(shard, weights_only=False)
            if state.get("world") == self._world:
                self.optimizer.load_state_dict(state["optimizer"])
            else:
                # Elastic resume at a different world size: params are
                # restored exactly; momentum restarts (same policy as a
                # deepspeed universal-checkpoint-less reshard).
                logger.warning(
                    "zero1: world size changed %s -> %s; optimizer state "
                    "reset", state.get("world"), self._world)

    # -- introspection (memory claim must be testable) -----------------
    def optimizer_state_numel(self) -> int:
        """Elements held in optimizer state on THIS rank."""
        total = 0
        for group_state in self.optimizer.state.values():
            for v in group_state.values():
                if torch.is_tensor(v):
                    total += v.numel()
        return total
