"""PyTorchTrial compatibility API.

Reference: harness/determined/pytorch/ (~5k LoC) — class-based trials where
the user overrides ``__init__ / train_batch / evaluate_batch /
build_*_data_loader`` (reference _pytorch_trial.py:1391,1471,1531,1544,1568)
and the controller owns the run loop (:548), driven by searcher operations
and the Core API.

TPU stance: the native compute path of this framework is JAX
(determined_tpu.train.JaxTrial); this module exists for API parity and
migration. It runs on whatever torch device is present — CPU in tests,
`torch_xla` devices when the task environment ships torch-xla (the
reference's CUDA/DDP path maps to torch-xla's xla backend; we select it when
importable).
"""

from determined_tpu.pytorch._trial import (  # noqa: F401
    DataLoader,
    PyTorchTrial,
    PyTorchTrialContext,
    Trainer,
    TorchData,
)
from determined_tpu.pytorch.deepspeed import (  # noqa: F401
    DeepSpeedTrial,
    DeepSpeedTrialContext,
    DeepSpeedTrainer,
    ModelParallelUnit,
)
from determined_tpu.pytorch.zero import ZeroOneEngine  # noqa: F401
