import sys

from determined_tpu.cli import main

sys.exit(main())
