"""`det` — the command-line interface.

Reference: harness/determined/cli/ (~9.2k LoC, declarative argparse). Covers
experiments, trials, checkpoints, users, workspaces/projects, the model
registry, templates, the job queue and master/agent admin against the
TPU-native master's REST API.

Usage: ``python -m determined_tpu.cli <command> ...`` (alias ``det`` when
installed as a console script).
"""

from __future__ import annotations

import argparse
import base64
import io
import json
import os
import ssl
import sys
import tarfile
import time
from typing import Any, Dict, Optional

from determined_tpu.common.api import APIError, Session
from determined_tpu import expconf

TOKEN_CACHE = os.path.expanduser("~/.config/determined_tpu/tokens.json")


def _load_config_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    import yaml

    return yaml.safe_load(text)


def _login(master: str, user: str, password: Optional[str] = None) -> Session:
    """Session with token cache (reference: authentication.login_with_cache)."""
    cache: Dict[str, str] = {}
    try:
        with open(TOKEN_CACHE) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        pass
    key = f"{master}::{user}"
    session = Session(master, cache.get(key))
    if cache.get(key):
        try:
            session.get("/api/v1/me")
            return session
        except APIError:
            pass
    if password is None:
        password = os.environ.get("DET_PASSWORD", "")
    from determined_tpu.common.api import salted_hash

    resp = Session(master).post(
        "/api/v1/auth/login",
        body={"username": user, "password": salted_hash(user, password)},
    )
    token = resp["token"]
    cache[key] = token
    os.makedirs(os.path.dirname(TOKEN_CACHE), exist_ok=True)
    with open(TOKEN_CACHE, "w") as f:
        json.dump(cache, f)
    return Session(master, token)


def _tar_context(context_dir: str) -> str:
    """Pack the model-def directory as base64 tar.gz (reference: context
    directory upload in cli/experiment.py submit_experiment)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for root, dirs, files in os.walk(context_dir):
            dirs[:] = [d for d in dirs if not d.startswith(".") and d != "__pycache__"]
            for name in files:
                full = os.path.join(root, name)
                arcname = os.path.relpath(full, context_dir)
                tar.add(full, arcname=arcname)
    raw = buf.getvalue()
    if len(raw) > 96 * 1024 * 1024:
        raise SystemExit("context directory exceeds 96MB limit")
    return base64.b64encode(raw).decode()


def _print_table(rows, columns) -> None:
    if not rows:
        print("(none)")
        return
    widths = [max(len(str(c)), max(len(str(r.get(c, ""))) for r in rows)) for c in columns]
    print(" | ".join(str(c).ljust(w) for c, w in zip(columns, widths)))
    print("-+-".join("-" * w for w in widths))
    for r in rows:
        print(" | ".join(str(r.get(c, "")).ljust(w) for c, w in zip(columns, widths)))


# ---------------------------------------------------------------------------
# experiment commands
# ---------------------------------------------------------------------------


def cmd_experiment_create(session: Session, args) -> int:
    config = _load_config_file(args.config)
    config = expconf.check(config)
    model_def = _tar_context(args.context_dir) if args.context_dir else ""
    resp = session.post(
        "/api/v1/experiments",
        body={
            "config": config,
            "model_definition": model_def,
            "activate": not args.paused,
            "project_id": args.project_id,
        },
    )
    eid = resp["id"]
    print(f"Created experiment {eid}")
    if args.follow:
        return _follow_experiment(session, eid)
    return 0


def _follow_experiment(session: Session, eid: int) -> int:
    last_state = None
    seen_logs: Dict[int, int] = {}
    while True:
        exp = session.get(f"/api/v1/experiments/{eid}")["experiment"]
        state = exp["state"]
        if state != last_state:
            print(f"experiment {eid}: {state} (progress {exp.get('progress', 0):.0%})")
            last_state = state
        trials = session.get(f"/api/v1/experiments/{eid}/trials")["trials"]
        for t in trials:
            offset = seen_logs.get(t["id"], 0)
            logs = session.get(
                f"/api/v1/tasks/trial-{t['id']}/logs", params={"offset": offset}
            )["logs"]
            for line in logs:
                print(f"[trial {t['id']}] {line['log']}")
                seen_logs[t["id"]] = max(seen_logs.get(t["id"], 0), line["id"])
        if state in ("COMPLETED", "CANCELED", "ERROR", "DELETED"):
            return 0 if state == "COMPLETED" else 1
        time.sleep(1.0)


def _page_params(args) -> dict:
    # Server-side pagination (master answers 400 past the caps).
    params = {}
    if getattr(args, "limit", None) is not None:
        params["limit"] = args.limit
    if getattr(args, "offset", None) is not None:
        params["offset"] = args.offset
    return params


def cmd_experiment_list(session: Session, args) -> int:
    exps = session.get("/api/v1/experiments",
                       params=_page_params(args) or None)["experiments"]
    rows = [
        {
            "id": e["id"],
            "name": (e.get("name") or ""),
            "state": e["state"],
            "progress": f"{(e.get('progress') or 0):.0%}",
            "started": e.get("start_time", ""),
        }
        for e in exps
    ]
    _print_table(rows, ["id", "name", "state", "progress", "started"])
    return 0


def cmd_experiment_verb(session: Session, args) -> int:
    if args.verb == "describe":
        print(json.dumps(session.get(f"/api/v1/experiments/{args.id}"), indent=2))
    elif args.verb == "delete":
        session.delete(f"/api/v1/experiments/{args.id}")
        print(f"deleted experiment {args.id}")
    else:
        session.post(f"/api/v1/experiments/{args.id}/{args.verb}")
        print(f"{args.verb} experiment {args.id}")
    return 0


def cmd_experiment_wait(session: Session, args) -> int:
    return _follow_experiment(session, args.id)


# ---------------------------------------------------------------------------
# trial / checkpoint / task commands
# ---------------------------------------------------------------------------


def cmd_trial_list(session: Session, args) -> int:
    trials = session.get(f"/api/v1/experiments/{args.experiment_id}/trials",
                         params=_page_params(args) or None)["trials"]
    rows = [
        {
            "id": t["id"],
            "state": t["state"],
            "batches": t.get("total_batches", 0),
            "metric": t.get("searcher_metric_value"),
            # Elastic trials run at a scheduler-chosen size; show what the
            # trial holds right now (docs/elasticity.md).
            "slots": t.get("current_slots", ""),
            "restarts": t.get("restarts", 0),
            "checkpoint": t.get("latest_checkpoint") or "",
        }
        for t in trials
    ]
    _print_table(rows, ["id", "state", "batches", "metric", "slots",
                        "restarts", "checkpoint"])
    return 0


def cmd_trial_describe(session: Session, args) -> int:
    print(json.dumps(session.get(f"/api/v1/trials/{args.id}"), indent=2))
    return 0


def cmd_trial_trace(session: Session, args) -> int:
    """Text waterfall of the trial's lifecycle trace: queue wait,
    container start, compile, restore, checkpoints, validation
    (docs/observability.md)."""
    from determined_tpu.common.trace import render_waterfall

    resp = session.get(f"/api/v1/trials/{args.id}/trace")
    spans = resp.get("spans", [])
    if args.json:
        print(json.dumps(resp, indent=2))
        return 0
    print(f"trial {args.id} trace {resp.get('trace_id') or '(none)'} — "
          f"{len(spans)} span(s)")
    print(render_waterfall(spans))
    return 0


def cmd_trial_logs(session: Session, args) -> int:
    offset = 0
    task_id = f"trial-{args.id}"
    while True:
        resp = session.get(
            f"/api/v1/tasks/{task_id}/logs",
            params={"offset": offset, "follow": "true" if args.follow else "false"},
            timeout=60.0,
        )
        logs = resp["logs"]
        for line in logs:
            print(line["log"])
            offset = max(offset, line["id"])
        if not args.follow and not logs:
            return 0
        if not logs:
            time.sleep(0.5)


def cmd_checkpoint_list(session: Session, args) -> int:
    cps = session.get(f"/api/v1/experiments/{args.experiment_id}/checkpoints",
                      params=_page_params(args) or None)["checkpoints"]
    rows = [
        {
            "uuid": c["uuid"],
            "trial": c.get("trial_id"),
            "steps": c.get("steps_completed"),
            "state": c.get("state"),
            "reported": c.get("report_time", ""),
        }
        for c in cps
    ]
    _print_table(rows, ["uuid", "trial", "steps", "state", "reported"])
    return 0


def cmd_checkpoint_describe(session: Session, args) -> int:
    print(json.dumps(session.get(f"/api/v1/checkpoints/{args.uuid}"), indent=2))
    return 0


def _show_task_state(t: dict) -> str:
    # A finished task's outcome (COMPLETED/ERROR/CANCELED) beats the
    # allocation's generic TERMINATED overlay.
    if t["state"] in ("COMPLETED", "ERROR", "CANCELED"):
        return t["state"]
    return t.get("allocation_state", t["state"])


def cmd_task_list(session: Session, args) -> int:
    params = {"type": args.type} if args.type else {}
    params.update(_page_params(args))
    tasks = session.get("/api/v1/tasks", params=params or None)["tasks"]
    rows = [
        {
            "id": t["id"],
            "type": t["type"],
            "state": _show_task_state(t),
            "started": t.get("start_time", ""),
            "ended": t.get("end_time") or "",
        }
        for t in tasks
    ]
    _print_table(rows, ["id", "type", "state", "started", "ended"])
    return 0


def cmd_task_logs(session: Session, args) -> int:
    ns = argparse.Namespace(id=None, follow=args.follow)
    offset = 0
    while True:
        resp = session.get(
            f"/api/v1/tasks/{args.task_id}/logs",
            params={"offset": offset, "follow": "true" if args.follow else "false"},
            timeout=60.0,
        )
        logs = resp["logs"]
        for line in logs:
            print(line["log"])
            offset = max(offset, line["id"])
        if not args.follow and not logs:
            return 0
        if not logs:
            time.sleep(0.5)


# ---------------------------------------------------------------------------
# NTSC task commands (reference: cli command/notebook/shell/tensorboard)
# ---------------------------------------------------------------------------


def cmd_ntsc(session: Session, args) -> int:
    kind = args.kind  # commands | notebooks | shells | tensorboards
    if args.action == "list":
        tasks = session.get(f"/api/v1/{kind}")[kind]
        rows = [
            {
                "id": t["id"],
                "state": _show_task_state(t),
                "started": t.get("start_time", ""),
                "address": t.get("proxy_address", ""),
            }
            for t in tasks
        ]
        _print_table(rows, ["id", "state", "started", "address"])
        return 0
    if args.action == "kill":
        session.post(f"/api/v1/{kind}/{args.task_id}/kill")
        print(f"killed {args.task_id}")
        return 0
    if args.action == "logs":
        ns = argparse.Namespace(task_id=args.task_id, follow=args.follow)
        return cmd_task_logs(session, ns)
    # start / run
    config: Dict[str, Any] = {}
    if getattr(args, "config_file", None):
        config = _load_config_file(args.config_file)
    if getattr(args, "cmd", None):
        config["entrypoint"] = args.cmd
    if getattr(args, "experiment_ids", None):
        config["experiment_ids"] = args.experiment_ids
    body: Dict[str, Any] = {"config": config}
    if getattr(args, "context", None):
        # Ship a context dir with the task (reference `det cmd run
        # --context`); extracted into the workdir, startup-hook.sh runs
        # before the entrypoint.
        body["context"] = _tar_context(args.context)
    resp = session.post(f"/api/v1/{kind}", body=body)
    print(f"Started {resp['id']} (allocation {resp['allocation_id']})")
    if kind in ("notebooks", "tensorboards"):
        # Wait briefly for the server address to be reported.
        for _ in range(60):
            task = session.get(f"/api/v1/{kind}/{resp['id']}")["task"]
            if task.get("proxy_address"):
                print(f"Serving at {task['proxy_address']}")
                break
            state = task.get("allocation_state", "")
            if state == "TERMINATED":
                print("task exited before serving; check `det task logs`")
                return 1
            time.sleep(1.0)
    return 0


def _open_tunnel(master: str, token: str, task_id: str, timeout: float = 60.0):
    """Open a det-tcp tunnel to a task through the master's proxy
    (reference cli/tunnel.py over proxy/tcp.go). Returns (socket, residual
    bytes received past the 101)."""
    import socket as socketlib
    import urllib.parse

    u = urllib.parse.urlparse(master)
    https = u.scheme == "https"
    host, port = u.hostname, u.port or (443 if https else 80)
    deadline = time.time() + timeout
    last_err = "no attempt"
    while time.time() < deadline:
        s = socketlib.create_connection((host, port), timeout=30)
        if https:
            from determined_tpu.common.api import _https_context

            try:
                s = _https_context().wrap_socket(s, server_hostname=host)
            except ssl.SSLCertVerificationError:
                s.close()
                raise  # retrying can't make an untrusted cert trusted
            except OSError as e:
                # Transient handshake failure (task still starting):
                # retry like every other transport error here.
                last_err = str(e)
                s.close()
                time.sleep(1.0)
                continue
        req = (
            f"GET /proxy/{task_id}/ HTTP/1.1\r\nHost: {host}\r\n"
            f"Authorization: Bearer {token}\r\n"
            f"Connection: Upgrade\r\nUpgrade: det-tcp\r\n\r\n"
        )
        s.sendall(req.encode())
        buf = b""
        try:
            while b"\r\n\r\n" not in buf:
                d = s.recv(4096)
                if not d:
                    raise ConnectionError("closed during handshake")
                buf += d
        except (OSError, ConnectionError) as e:
            last_err = str(e)
            s.close()
            time.sleep(1.0)
            continue
        head, rest = buf.split(b"\r\n\r\n", 1)
        status = head.split(b"\r\n", 1)[0]
        if b"101" in status:
            s.settimeout(None)
            return s, rest
        s.close()
        last_err = status.decode(errors="replace")
        # 502 until the task reports its address — keep retrying.
        time.sleep(1.0)
    raise SystemExit(f"could not open tunnel to {task_id}: {last_err}")


def cmd_shell(session: Session, args) -> int:
    if args.action in ("list", "kill", "logs", "start"):
        return cmd_ntsc(session, args)
    task_id = args.task_id
    s, rest = _open_tunnel(session.master_url, session.token, task_id)
    if args.action == "run":
        script = " ".join(args.cmd) + "\n"
        s.sendall(script.encode())
        s.shutdown(1)  # SHUT_WR: half-close ends the remote shell's stdin
        if rest:
            sys.stdout.buffer.write(rest)
        while True:
            d = s.recv(65536)
            if not d:
                break
            sys.stdout.buffer.write(d)
            sys.stdout.buffer.flush()
        s.close()
        return 0
    # interactive `det shell open`: bridge stdin/stdout over the tunnel.
    import threading

    if rest:
        sys.stdout.buffer.write(rest)
        sys.stdout.buffer.flush()

    def pump_out():
        while True:
            d = s.recv(65536)
            if not d:
                break
            sys.stdout.buffer.write(d)
            sys.stdout.buffer.flush()

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    try:
        while True:
            line = sys.stdin.buffer.readline()
            if not line:
                break
            s.sendall(line)
    except KeyboardInterrupt:
        pass
    s.shutdown(1)
    t.join(timeout=5.0)
    s.close()
    return 0


# ---------------------------------------------------------------------------
# preflight — static trial analysis, no master/session needed
# ---------------------------------------------------------------------------


def cmd_preflight(session, args) -> int:
    """`det preflight <config> [context_dir]` — run the static analyzer
    (docs/preflight.md) over an experiment config + model-def directory
    and exit nonzero on unsuppressed error-level findings. Pure local
    analysis: no master connection, no TPU time."""
    from determined_tpu import analysis

    config = _load_config_file(args.config)
    report = analysis.preflight(config, context_dir=args.context_dir,
                                load_trials=not args.no_trial)
    if args.json:
        print(report.to_json())
    else:
        print(report.to_text())
    return 1 if report.errors else 0


# ---------------------------------------------------------------------------
# serve — inference serving from trained checkpoints (docs/serving.md)
# ---------------------------------------------------------------------------


def cmd_serve(session, args) -> int:
    """`det serve <config> [context_dir]` — launch a serve replica, or a
    deployment when the config carries `serving.replicas`;
    `det serve status [id]` — list/inspect (deployments + tasks);
    `det serve scale <deployment> <target>` — manual scale within
    [min, max]; `det serve kill <id>` — kill a task or a deployment.

    `--local` runs the replica in-process against local checkpoint
    storage (no master) — the dev loop for serving configs."""
    target = args.target
    if target == "status":
        if args.extra:
            tid = args.extra[0]
            if tid.startswith("deploy-"):
                resp = session.get(f"/api/v1/deployments/{tid}")
                d = resp.get("deployment", resp)
                # Canary-vs-stable latency side by side (docs/serving.md
                # "Model lifecycle") before the full JSON dump.
                byv = d.get("latency_by_version") or {}
                if len(byv) > 1:
                    rows = []
                    for version, lat in sorted(byv.items()):
                        row = {"version": version}
                        for key in ("ttft", "tpot", "e2e"):
                            h = lat.get(key) or {}
                            row[f"{key}_ms"] = (
                                f"{h['p50_ms']:.0f}/{h['p99_ms']:.0f}"
                                if h.get("count") else "-")
                        rows.append(row)
                    _print_table(rows,
                                 ["version", "ttft_ms", "tpot_ms",
                                  "e2e_ms"])
                    print("  (per-version p50/p99 ms over fresh replica "
                          "heartbeats)")
                print(json.dumps(d, indent=2))
                return 0
            resp = session.get(f"/api/v1/serving/{tid}")
            print(json.dumps(resp.get("task", resp), indent=2))
            return 0
        deployments = session.get(
            "/api/v1/deployments").get("deployments", [])
        if deployments:

            def _pp(d, key):
                """'p50/p99 ms' from the aggregated latency summary —
                fresh-heartbeat merged across the replica set."""
                h = (d.get("latency") or {}).get(key) or {}
                if not h.get("count"):
                    return "-"
                return f"{h['p50_ms']:.0f}/{h['p99_ms']:.0f}"

            def _version_col(d):
                """Served version (+ swap arrow while rolling) and the
                canary split, compact enough for a table cell."""
                v = d.get("model_version") or ""
                v = v.replace("checkpoint:", "ckpt:")
                if d.get("swapping"):
                    v = f"->{v}"
                return v

            def _canary_col(d):
                c = d.get("canary")
                if not c:
                    return ""
                return (f"{c.get('version')}@{c.get('fraction')}"
                        f" (obs {c.get('observed_fraction', 0):.2f})")

            _print_table(
                [
                    {
                        "id": d.get("id"),
                        "name": d.get("name"),
                        "state": d.get("state"),
                        "replicas": (f"{d.get('replica_count', 0)}"
                                     f"/{d.get('target_replicas', 0)}"),
                        "range": (f"[{d.get('min_replicas')}, "
                                  f"{d.get('max_replicas')}]"),
                        "version": _version_col(d),
                        "canary": _canary_col(d),
                        "load": round(d.get("smoothed_load") or 0.0, 3),
                        "ttft_ms": _pp(d, "ttft"),
                        "tpot_ms": _pp(d, "tpot"),
                        "e2e_ms": _pp(d, "e2e"),
                    }
                    for d in deployments
                ],
                ["id", "name", "state", "replicas", "range", "version",
                 "canary", "load", "ttft_ms", "tpot_ms", "e2e_ms"])
            print("  (latency columns are p50/p99 ms over fresh replica "
                  "heartbeats)")
        resp = session.get("/api/v1/serving")
        rows = [
            {
                "id": t.get("id"),
                "state": t.get("state"),
                "allocation": t.get("allocation_state", ""),
                "address": t.get("proxy_address", ""),
                "restarts": t.get("restarts", 0),
            }
            for t in resp.get("serving", [])
        ]
        _print_table(rows, ["id", "state", "allocation", "address",
                            "restarts"])
        return 0
    if target == "scale":
        if len(args.extra) != 2:
            raise SystemExit(
                "usage: det serve scale <deployment-id> <target>")
        dep, n = args.extra[0], int(args.extra[1])
        resp = session.post(f"/api/v1/deployments/{dep}/scale",
                            body={"target": n})
        print(f"deployment {resp.get('id', dep)} target -> "
              f"{resp.get('target', n)}")
        return 0
    if target == "update":
        # `det serve update <deployment> <model[:version] | checkpoint>`
        # — rolling blue-green weight swap (docs/serving.md "Model
        # lifecycle"): spawn-at-new before drain-at-old, one replica at
        # a time, zero dropped. Rollback = update back to the prior
        # version (registered versions stay resident in the registry).
        if len(args.extra) != 2:
            raise SystemExit(
                "usage: det serve update <deployment> "
                "<model[:version] | checkpoint-id>")
        dep, spec = args.extra
        resp = session.post(f"/api/v1/deployments/{dep}/update",
                            body=_version_spec_body(spec))
        if resp.get("rolling"):
            print(f"deployment {resp.get('id', dep)} rolling to "
                  f"{resp.get('model_version')} "
                  f"(checkpoint {resp.get('checkpoint')})")
            print(f"  watch:  det serve status {resp.get('id', dep)}")
        else:
            print(f"deployment {resp.get('id', dep)} already serves "
                  f"{resp.get('model_version')}")
        return 0
    if target == "canary":
        # `det serve canary <deployment> <version> --fraction 0.05`,
        # then `--promote` (fold into the deployment via a rolling swap)
        # or `--abort` (drain the canary, stable untouched).
        if not args.extra:
            raise SystemExit(
                "usage: det serve canary <deployment> "
                "[<model[:version] | checkpoint>] [--fraction F] "
                "[--replicas N] | --promote | --abort")
        dep = args.extra[0]
        if getattr(args, "promote", False):
            resp = session.post(f"/api/v1/deployments/{dep}/canary",
                                body={"promote": True})
            stats = resp.get("canary_stats") or {}
            print(f"promoted {resp.get('promoted')} on "
                  f"{resp.get('id', dep)} (canary served "
                  f"{stats.get('routed', 0)} of "
                  f"{stats.get('routed', 0) + stats.get('routed_stable', 0)}"
                  " generations); remaining replicas rolling over")
            return 0
        if getattr(args, "abort", False):
            resp = session.post(f"/api/v1/deployments/{dep}/canary",
                                body={"abort": True})
            print(f"aborted canary {resp.get('aborted')} on "
                  f"{resp.get('id', dep)}; canary replicas draining")
            return 0
        if len(args.extra) != 2:
            raise SystemExit(
                "usage: det serve canary <deployment> "
                "<model[:version] | checkpoint> --fraction F")
        body = _version_spec_body(args.extra[1])
        body["fraction"] = float(getattr(args, "fraction", 0.05) or 0.05)
        if getattr(args, "replicas", None):
            body["replicas"] = int(args.replicas)
        resp = session.post(f"/api/v1/deployments/{dep}/canary", body=body)
        print(f"canary {resp.get('canary')} on {resp.get('id', dep)}: "
              f"{resp.get('fraction')} of traffic, "
              f"{resp.get('replicas')} replica(s)")
        print(f"  compare: det serve status {resp.get('id', dep)} "
              "(per-version p50/p99)")
        print(f"  promote: det serve canary {resp.get('id', dep)} "
              "--promote")
        print(f"  abort:   det serve canary {resp.get('id', dep)} --abort")
        return 0
    if target == "trace":
        # `det serve trace <deployment> <request-id>` — the request's
        # router→replica span tree as the same text waterfall `det trial
        # trace` renders (docs/observability.md "Request spans").
        if len(args.extra) != 2:
            raise SystemExit(
                "usage: det serve trace <deployment> <request-id>")
        from determined_tpu.common.trace import render_waterfall

        dep, rid = args.extra
        resp = session.get(
            f"/api/v1/deployments/{dep}/requests/{rid}/trace")
        spans = resp.get("spans", [])
        if getattr(args, "json", False):
            print(json.dumps(spans, indent=2))
            return 0
        print(f"request {rid} on {resp.get('deployment_id', dep)} — "
              f"{len(spans)} span(s)")
        print(render_waterfall(spans))
        return 0
    if target == "kill":
        if not args.extra:
            raise SystemExit("usage: det serve kill <task-or-deployment-id>")
        tid = args.extra[0]
        if tid.startswith("deploy-"):
            session.post(f"/api/v1/deployments/{tid}/kill")
        else:
            session.post(f"/api/v1/serving/{tid}/kill")
        print(f"killed {tid}")
        return 0

    # Launch path: <config> [context_dir].
    config = expconf.check(_load_config_file(target))
    if "serving" not in config:
        raise SystemExit(
            "config has no `serving:` block (docs/serving.md)")
    if args.local:
        from determined_tpu.serve import task as serve_task

        os.environ["DET_SERVING_CONFIG"] = json.dumps(config)
        return serve_task.main([])
    context_dir = args.extra[0] if args.extra else None
    body = {"config": config}
    if context_dir:
        body["context"] = _tar_context(context_dir)
    if isinstance(config["serving"].get("replicas"), dict):
        # serving.replicas makes this a deployment: a reconciled replica
        # set behind the /serve/{deployment} router, autoscaled within
        # [min, max] (docs/serving.md "Deployments & autoscaling").
        resp = session.post("/api/v1/deployments", body=body)
        print(f"Created deployment {resp['id']} "
              f"({resp.get('target')} replicas: "
              f"{', '.join(resp.get('replicas', []))})")
        print("  status:  det serve status " + resp["id"])
        print(f"  scale:   det serve scale {resp['id']} <target>")
        print(f"  route:   POST /serve/{resp['id']}/v1/generate")
        return 0
    resp = session.post("/api/v1/serving", body=body)
    print(f"Created serving task {resp['id']} "
          f"(allocation {resp.get('allocation_id')})")
    print("  status:  det serve status")
    print(f"  address: GET /api/v1/serving/{resp['id']} → proxy_address")
    return 0


def cmd_deploy(session: Session, args) -> int:
    from determined_tpu import deploy as deploy_mod

    if args.target == "local":
        if args.action == "up":
            state = deploy_mod.cluster_up(port=args.port, agents=args.agents,
                                          slots=args.slots,
                                          tls=getattr(args, "tls", False))
            print(f"cluster up: master pid {state['master_pid']} on port "
                  f"{state['port']}; logs in {state['logs']}")
            if state.get("tls"):
                print(f"TLS on: export DET_MASTER_CERT_FILE={state['cert']}")
        elif args.action == "down":
            print("cluster stopped" if deploy_mod.cluster_down()
                  else "no local cluster running")
        else:
            state = deploy_mod.cluster_status()
            if state is None:
                print("no local cluster running")
            else:
                print(json.dumps(state, indent=2))
    elif args.target == "gke":
        from determined_tpu.deploy import gke

        out = gke.generate(args.target_dir, project=args.project,
                           cluster=args.cluster, zone=args.zone,
                           namespace=args.namespace,
                           slots_per_pod=args.slots_per_pod,
                           num_nodes=args.num_nodes)
        print(f"manifests written to {out}; review then "
              f"`bash {out}/cluster.sh && kubectl apply -f {out}`")
    else:  # gcp
        from determined_tpu.deploy import gcp

        out = gcp.generate(args.target_dir, project=args.project,
                           zone=args.zone,
                           accelerator_type=args.accelerator_type,
                           num_slices=args.num_slices)
        print(f"terraform written to {out}; review then `terraform apply`")
    return 0


def cmd_master_info(session: Session, args) -> int:
    print(json.dumps(session.get("/api/v1/master"), indent=2))
    return 0


def cmd_agent_list(session: Session, args) -> int:
    agents = session.get("/api/v1/agents")["agents"]
    rows = [
        {
            "id": a["id"],
            "pool": a["resource_pool"],
            # Capacity tier (docs/cluster-ops.md "Capacity loop"): spot
            # nodes are reclaimable surplus; deployment floors avoid them.
            "class": "spot" if a.get("preemptible") else "on-demand",
            "alive": a["alive"],
            "state": a.get("state", "ENABLED")
            + (f" ({a['drain_reason']})" if a.get("drain_reason") else ""),
            "slots": len(a["slots"]),
            "used": sum(1 for s in a["slots"] if s.get("allocation_id")),
        }
        for a in agents
    ]
    _print_table(rows, ["id", "pool", "class", "alive", "state", "slots",
                        "used"])
    return 0


def cmd_job_list(session: Session, args) -> int:
    jobs = session.get("/api/v1/job-queues")["jobs"]
    _print_table(jobs, ["allocation_id", "experiment_id", "state", "slots", "priority"])
    return 0


def cmd_compile_jobs(session: Session, args) -> int:
    """Compile-farm queue visibility (docs/compile-farm.md): what is
    queued/compiling/done, which agent took it, and the measured cost."""
    params = {}
    if getattr(args, "state", None):
        params["state"] = args.state
    if getattr(args, "experiment_id", None):
        params["experiment_id"] = str(args.experiment_id)
    jobs = session.get("/api/v1/compile_jobs", params=params or None)["jobs"]
    rows = [dict(j, signature=(j.get("signature") or "")[:16],
                 compile_ms=round(j["compile_ms"], 1)
                 if isinstance(j.get("compile_ms"), (int, float)) else "")
            for j in jobs]
    _print_table(rows, ["signature", "state", "experiment_id", "slots",
                        "attempts", "agent_id", "compile_ms"])
    return 0


def cmd_user_list(session: Session, args) -> int:
    users = session.get("/api/v1/users")["users"]
    _print_table(users, ["id", "username", "role", "active"])
    return 0


def cmd_user_create(session: Session, args) -> int:
    from determined_tpu.common.api import salted_hash

    role = "admin" if getattr(args, "admin", False) else args.role
    session.post(
        "/api/v1/users",
        body={"username": args.username, "role": role,
              "password": salted_hash(args.username, args.password or "")},
    )
    print(f"created user {args.username} (role {role})")
    return 0


def _user_by_name(session: Session, name_or_id: str) -> Dict[str, Any]:
    for u in session.get("/api/v1/users")["users"]:
        if u["username"] == name_or_id or (
            name_or_id.isdigit() and u["id"] == int(name_or_id)
        ):
            return u
    raise SystemExit(f"no such user: {name_or_id}")


def _user_id_by_name(session: Session, name_or_id: str) -> int:
    return _user_by_name(session, name_or_id)["id"]


def cmd_user_patch(session: Session, args) -> int:
    user = _user_by_name(session, args.target_user)
    uid = user["id"]
    body: Dict[str, Any] = {}
    if args.action == "activate":
        body["active"] = True
    elif args.action == "deactivate":
        body["active"] = False
    elif args.action == "change-role":
        body["role"] = args.role
    elif args.action == "change-password":
        from determined_tpu.common.api import salted_hash

        # Salt with the USERNAME (login salts with it) — a numeric-id
        # target must resolve to the name first or the hashes never match.
        body["password"] = salted_hash(user["username"], args.password)
    session.patch(f"/api/v1/users/{uid}", body=body)
    print(f"{args.action} user {args.target_user}")
    return 0


def cmd_user_whoami(session: Session, args) -> int:
    me = session.get("/api/v1/me")["user"]
    print(f"{me['username']} (id {me['id']}, role {me.get('role', 'user')})")
    return 0


def cmd_rbac(session: Session, args) -> int:
    if args.action == "list":
        params = {}
        if getattr(args, "workspace_id", None) is not None:
            params["workspace_id"] = args.workspace_id
        rows = session.get("/api/v1/rbac/assignments", params=params)["assignments"]
        _print_table(rows, ["id", "role", "username", "group_name", "workspace_id"])
        return 0
    if args.action == "unassign":
        session.delete(f"/api/v1/rbac/assignments/{args.id}")
        print(f"removed assignment {args.id}")
        return 0
    body: Dict[str, Any] = {"role": args.role}
    if args.target_user:
        body["user_id"] = _user_id_by_name(session, args.target_user)
    if args.group_id is not None:
        body["group_id"] = args.group_id
    if args.workspace_id is not None:
        body["workspace_id"] = args.workspace_id
    resp = session.post("/api/v1/rbac/assignments", body=body)
    print(f"assigned {args.role} (assignment {resp['id']})")
    return 0


def cmd_group(session: Session, args) -> int:
    if args.action == "list":
        groups = session.get("/api/v1/groups")["groups"]
        rows = [
            {"id": g["id"], "name": g["name"],
             "members": ",".join(m["username"] for m in g["members"])}
            for g in groups
        ]
        _print_table(rows, ["id", "name", "members"])
    elif args.action == "create":
        resp = session.post("/api/v1/groups", body={"name": args.name})
        print(f"created group {args.name} (id {resp['id']})")
    elif args.action == "add-member":
        uid = _user_id_by_name(session, args.target_user)
        session.post(f"/api/v1/groups/{args.group_id}/members",
                     body={"user_id": uid})
        print(f"added {args.target_user} to group {args.group_id}")
    elif args.action == "remove-member":
        uid = _user_id_by_name(session, args.target_user)
        session.delete(f"/api/v1/groups/{args.group_id}/members/{uid}")
        print(f"removed {args.target_user} from group {args.group_id}")
    return 0


def cmd_agent_admin(session: Session, args) -> int:
    session.post(f"/api/v1/agents/{args.agent_id}/{args.action}")
    print(f"{args.action}d agent {args.agent_id}")
    return 0


def cmd_workspace(session: Session, args) -> int:
    if args.action == "list":
        _print_table(session.get("/api/v1/workspaces")["workspaces"],
                     ["id", "name", "archived"])
    else:
        session.post("/api/v1/workspaces", body={"name": args.name})
        print(f"created workspace {args.name}")
    return 0


def cmd_project(session: Session, args) -> int:
    if args.action == "list":
        _print_table(
            session.get(f"/api/v1/workspaces/{args.workspace_id}/projects")["projects"],
            ["id", "name", "workspace_id", "archived"],
        )
    else:
        session.post(
            "/api/v1/projects",
            body={"name": args.name, "workspace_id": args.workspace_id},
        )
        print(f"created project {args.name}")
    return 0


def cmd_model(session: Session, args) -> int:
    if args.action == "list":
        _print_table(session.get("/api/v1/models")["models"],
                     ["id", "name", "description", "archived"])
    elif args.action == "create":
        session.post("/api/v1/models", body={"name": args.name, "metadata": {},
                                             "labels": []})
        print(f"created model {args.name}")
    elif args.action == "describe":
        print(json.dumps(session.get(f"/api/v1/models/{args.name}"), indent=2))
    elif args.action == "register-version":
        resp = session.post(
            f"/api/v1/models/{args.name}/versions",
            body={"checkpoint_uuid": args.uuid, "metadata": {}},
        )
        print(f"registered version {resp['model_version']['version']}")
    elif args.action == "versions":
        _print_table(
            session.get(f"/api/v1/models/{args.name}/versions")["model_versions"],
            ["id", "version", "checkpoint_uuid", "source_experiment_id",
             "source_trial_id", "steps_completed", "creation_time"],
        )
    return 0


def _version_spec_body(spec: str) -> dict:
    """'<model>:<version>' / '<model>:latest' → registry coordinates;
    anything without a colon is a raw checkpoint storage id."""
    if ":" in spec:
        model, _, ver = spec.rpartition(":")
        body = {"model": model}
        if ver and ver != "latest":
            try:
                body["version"] = int(ver)
            except ValueError:
                raise SystemExit(
                    f"bad version spec {spec!r}: want <model>:<int> or "
                    "<model>:latest")
        return body
    return {"checkpoint": spec}


def cmd_template(session: Session, args) -> int:
    if args.action == "list":
        _print_table(session.get("/api/v1/templates")["templates"], ["name"])
    else:
        config = _load_config_file(args.config)
        session.post("/api/v1/templates", body={"name": args.name, "config": config})
        print(f"set template {args.name}")
    return 0


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="det", description=__doc__)
    p.add_argument("-m", "--master", default=os.environ.get("DET_MASTER",
                                                            "http://127.0.0.1:8080"))
    p.add_argument("-u", "--user", default=os.environ.get("DET_USER", "determined"))
    sub = p.add_subparsers(dest="command", required=True)

    exp = sub.add_parser("experiment", aliases=["e"]).add_subparsers(
        dest="subcommand", required=True)
    c = exp.add_parser("create")
    c.add_argument("config")
    c.add_argument("context_dir", nargs="?")
    c.add_argument("--paused", action="store_true")
    c.add_argument("-f", "--follow", action="store_true")
    c.add_argument("--project-id", type=int, default=1)
    c.set_defaults(func=cmd_experiment_create)
    el = exp.add_parser("list")
    el.add_argument("--limit", type=int, default=None,
                    help="page size (server caps at 1000)")
    el.add_argument("--offset", type=int, default=None)
    el.set_defaults(func=cmd_experiment_list)
    for verb in ("describe", "activate", "pause", "cancel", "kill", "archive",
                 "unarchive", "delete"):
        v = exp.add_parser(verb)
        v.add_argument("id", type=int)
        v.set_defaults(func=cmd_experiment_verb, verb=verb)
    w = exp.add_parser("wait")
    w.add_argument("id", type=int)
    w.set_defaults(func=cmd_experiment_wait)

    tr = sub.add_parser("trial", aliases=["t"]).add_subparsers(
        dest="subcommand", required=True)
    t = tr.add_parser("list")
    t.add_argument("experiment_id", type=int)
    t.add_argument("--limit", type=int, default=None,
                   help="page size (server caps at 1000)")
    t.add_argument("--offset", type=int, default=None)
    t.set_defaults(func=cmd_trial_list)
    t = tr.add_parser("describe")
    t.add_argument("id", type=int)
    t.set_defaults(func=cmd_trial_describe)
    t = tr.add_parser("logs")
    t.add_argument("id", type=int)
    t.add_argument("-f", "--follow", action="store_true")
    t.set_defaults(func=cmd_trial_logs)
    t = tr.add_parser("trace")
    t.add_argument("id", type=int)
    t.add_argument("--json", action="store_true")
    t.set_defaults(func=cmd_trial_trace)

    cp = sub.add_parser("checkpoint").add_subparsers(dest="subcommand", required=True)
    c = cp.add_parser("list")
    c.add_argument("experiment_id", type=int)
    c.add_argument("--limit", type=int, default=None,
                   help="page size (server caps at 1000)")
    c.add_argument("--offset", type=int, default=None)
    c.set_defaults(func=cmd_checkpoint_list)
    c = cp.add_parser("describe")
    c.add_argument("uuid")
    c.set_defaults(func=cmd_checkpoint_describe)

    tk = sub.add_parser("task").add_subparsers(dest="subcommand", required=True)
    t = tk.add_parser("logs")
    t.add_argument("task_id")
    t.add_argument("-f", "--follow", action="store_true")
    t.set_defaults(func=cmd_task_logs)
    tl = tk.add_parser("list")
    tl.add_argument("--type", default=None,
                    help="TRIAL|COMMAND|NOTEBOOK|SHELL|TENSORBOARD|GENERIC|GC")
    tl.add_argument("--limit", type=int, default=None,
                    help="page size (server caps at 1000)")
    tl.add_argument("--offset", type=int, default=None)
    tl.set_defaults(func=cmd_task_list)

    for cli_name, kind in (("cmd", "commands"), ("notebook", "notebooks"),
                           ("shell", "shells"), ("tensorboard", "tensorboards")):
        nt = sub.add_parser(cli_name).add_subparsers(dest="subcommand",
                                                     required=True)
        start = nt.add_parser("run" if cli_name == "cmd" else "start")
        if cli_name == "cmd":
            # REMAINDER so flags in the command (`det cmd run ls -la`)
            # reach the task instead of argparse.
            start.add_argument("cmd", nargs=argparse.REMAINDER)
        if cli_name == "tensorboard":
            start.add_argument("experiment_ids", type=int, nargs="+")
        start.add_argument("--config-file")
        start.add_argument("--context", metavar="DIR",
                           help="directory shipped to the task workdir")
        start.set_defaults(func=cmd_ntsc, kind=kind, action="start")
        nt.add_parser("list").set_defaults(func=cmd_ntsc, kind=kind,
                                           action="list")
        k = nt.add_parser("kill")
        k.add_argument("task_id")
        k.set_defaults(func=cmd_ntsc, kind=kind, action="kill")
        lg = nt.add_parser("logs")
        lg.add_argument("task_id")
        lg.add_argument("-f", "--follow", action="store_true")
        lg.set_defaults(func=cmd_ntsc, kind=kind, action="logs")
        if cli_name == "shell":
            so = nt.add_parser("open")
            so.add_argument("task_id")
            so.set_defaults(func=cmd_shell, kind=kind, action="open")
            sr = nt.add_parser("run")
            sr.add_argument("task_id")
            sr.add_argument("cmd", nargs=argparse.REMAINDER)
            sr.set_defaults(func=cmd_shell, kind=kind, action="run")

    m = sub.add_parser("master").add_subparsers(dest="subcommand", required=True)
    m.add_parser("info").set_defaults(func=cmd_master_info)

    a = sub.add_parser("agent").add_subparsers(dest="subcommand", required=True)
    a.add_parser("list").set_defaults(func=cmd_agent_list)
    for action in ("enable", "disable"):
        av = a.add_parser(action)
        av.add_argument("agent_id")
        av.set_defaults(func=cmd_agent_admin, action=action)

    j = sub.add_parser("job").add_subparsers(dest="subcommand", required=True)
    j.add_parser("list").set_defaults(func=cmd_job_list)

    cj = sub.add_parser(
        "compile",
        help="compile-farm AOT queue and artifacts (docs/compile-farm.md)"
    ).add_subparsers(dest="subcommand", required=True)
    cjl = cj.add_parser("jobs")
    cjl.add_argument("--state", default=None,
                     help="QUEUED|RUNNING|DONE|FAILED")
    cjl.add_argument("--experiment-id", type=int, default=None)
    cjl.set_defaults(func=cmd_compile_jobs)

    u = sub.add_parser("user").add_subparsers(dest="subcommand", required=True)
    u.add_parser("list").set_defaults(func=cmd_user_list)
    u.add_parser("whoami").set_defaults(func=cmd_user_whoami)
    uc = u.add_parser("create")
    uc.add_argument("username")
    uc.add_argument("--role", choices=["admin", "user", "viewer"], default="user")
    uc.add_argument("--admin", action="store_true")
    uc.add_argument("--password", default="")
    uc.set_defaults(func=cmd_user_create)
    for action in ("activate", "deactivate"):
        ua = u.add_parser(action)
        ua.add_argument("target_user", metavar="user")
        ua.set_defaults(func=cmd_user_patch, action=action)
    ur = u.add_parser("change-role")
    ur.add_argument("target_user", metavar="user")
    ur.add_argument("role", choices=["admin", "user", "viewer"])
    ur.set_defaults(func=cmd_user_patch, action="change-role")
    up2 = u.add_parser("change-password")
    up2.add_argument("target_user", metavar="user")
    up2.add_argument("password")
    up2.set_defaults(func=cmd_user_patch, action="change-password")

    rb = sub.add_parser("rbac").add_subparsers(dest="subcommand", required=True)
    rl = rb.add_parser("list")
    rl.add_argument("--workspace-id", type=int, default=None)
    rl.set_defaults(func=cmd_rbac, action="list")
    ra = rb.add_parser("assign")
    ra.add_argument("role", choices=["viewer", "editor", "admin"])
    ra.add_argument("--user", dest="target_user", default=None)
    ra.add_argument("--group-id", type=int, default=None)
    ra.add_argument("--workspace-id", type=int, default=None)
    ra.set_defaults(func=cmd_rbac, action="assign")
    ru = rb.add_parser("unassign")
    ru.add_argument("id", type=int)
    ru.set_defaults(func=cmd_rbac, action="unassign")

    gr = sub.add_parser("group").add_subparsers(dest="subcommand", required=True)
    gr.add_parser("list").set_defaults(func=cmd_group, action="list")
    gc = gr.add_parser("create")
    gc.add_argument("name")
    gc.set_defaults(func=cmd_group, action="create")
    for action in ("add-member", "remove-member"):
        ga = gr.add_parser(action)
        ga.add_argument("group_id", type=int)
        ga.add_argument("target_user", metavar="user")
        ga.set_defaults(func=cmd_group, action=action)

    ws = sub.add_parser("workspace").add_subparsers(dest="subcommand", required=True)
    ws.add_parser("list").set_defaults(func=cmd_workspace, action="list")
    wc = ws.add_parser("create")
    wc.add_argument("name")
    wc.set_defaults(func=cmd_workspace, action="create")

    pj = sub.add_parser("project").add_subparsers(dest="subcommand", required=True)
    pl = pj.add_parser("list")
    pl.add_argument("workspace_id", type=int)
    pl.set_defaults(func=cmd_project, action="list")
    pc = pj.add_parser("create")
    pc.add_argument("workspace_id", type=int)
    pc.add_argument("name")
    pc.set_defaults(func=cmd_project, action="create")

    md = sub.add_parser("model").add_subparsers(dest="subcommand", required=True)
    md.add_parser("list").set_defaults(func=cmd_model, action="list")
    mc = md.add_parser("create")
    mc.add_argument("name")
    mc.set_defaults(func=cmd_model, action="create")
    mdd = md.add_parser("describe")
    mdd.add_argument("name")
    mdd.set_defaults(func=cmd_model, action="describe")
    mv = md.add_parser("register-version")
    mv.add_argument("name")
    mv.add_argument("uuid")
    mv.set_defaults(func=cmd_model, action="register-version")
    mvs = md.add_parser("versions")
    mvs.add_argument("name")
    mvs.set_defaults(func=cmd_model, action="versions")

    dp = sub.add_parser("deploy").add_subparsers(dest="subcommand", required=True)
    dl = dp.add_parser("local").add_subparsers(dest="subsubcommand", required=True)
    up = dl.add_parser("up")
    up.add_argument("--port", type=int, default=8080)
    up.add_argument("--agents", type=int, default=1)
    up.add_argument("--slots", type=int, default=None)
    up.add_argument("--tls", action="store_true",
                    help="serve HTTPS with a generated self-signed cert")
    up.set_defaults(func=cmd_deploy, target="local", action="up")
    dl.add_parser("down").set_defaults(func=cmd_deploy, target="local",
                                       action="down")
    dl.add_parser("status").set_defaults(func=cmd_deploy, target="local",
                                         action="status")
    dg = dp.add_parser("gcp")
    dg.add_argument("target_dir")
    dg.add_argument("--project", required=True)
    dg.add_argument("--zone", default="us-east5-b")
    dg.add_argument("--accelerator-type", default="v5litepod-8")
    dg.add_argument("--num-slices", type=int, default=1)
    dg.set_defaults(func=cmd_deploy, target="gcp")
    dk = dp.add_parser("gke")
    dk.add_argument("target_dir")
    dk.add_argument("--project", required=True)
    dk.add_argument("--cluster", default="determined-tpu")
    dk.add_argument("--zone", default="us-east5-b")
    dk.add_argument("--namespace", default="default")
    dk.add_argument("--slots-per-pod", type=int, default=4)
    dk.add_argument("--num-nodes", type=int, default=2)
    dk.set_defaults(func=cmd_deploy, target="gke")

    sv = sub.add_parser(
        "serve",
        help="high-throughput inference serving from trained checkpoints "
             "(docs/serving.md)")
    sv.add_argument(
        "target",
        help="serving config file to launch, or 'status' / 'scale' / "
             "'kill' / 'trace' / 'update' / 'canary'")
    sv.add_argument(
        "extra", nargs="*",
        help="context dir (launch), task/deployment id (status/kill), "
             "<deployment-id> <target> (scale), "
             "<deployment> <request-id> (trace), or "
             "<deployment> <model[:version]|checkpoint> (update/canary)")
    sv.add_argument(
        "--local", action="store_true",
        help="run the replica in-process against local storage (no master)")
    sv.add_argument(
        "--json", action="store_true",
        help="raw span JSON instead of the waterfall (trace)")
    sv.add_argument(
        "--fraction", type=float, default=0.05,
        help="canary traffic fraction in (0, 1) (canary; default 0.05)")
    sv.add_argument(
        "--replicas", type=int, default=None,
        help="canary replica count (canary; default 1)")
    sv.add_argument(
        "--promote", action="store_true",
        help="fold the canary version into the deployment (canary)")
    sv.add_argument(
        "--abort", action="store_true",
        help="drain the canary replicas, keep stable untouched (canary)")
    sv.set_defaults(func=cmd_serve)

    pf = sub.add_parser(
        "preflight",
        help="static shard/HBM/recompile analysis of a trial config "
             "before any TPU time is spent")
    pf.add_argument("config")
    pf.add_argument("context_dir", nargs="?", default=None)
    pf.add_argument("--json", action="store_true",
                    help="emit structured JSON instead of human text")
    pf.add_argument("--no-trial", action="store_true",
                    help="skip importing the trial class (config + AST "
                         "lint only)")
    pf.set_defaults(func=cmd_preflight)

    tp = sub.add_parser("template").add_subparsers(dest="subcommand", required=True)
    tp.add_parser("list").set_defaults(func=cmd_template, action="list")
    ts = tp.add_parser("set")
    ts.add_argument("name")
    ts.add_argument("config")
    ts.set_defaults(func=cmd_template, action="set")

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # deploy/preflight (and serve --local) run locally — no session/login.
    local = args.func in (cmd_deploy, cmd_preflight) or (
        args.func is cmd_serve and getattr(args, "local", False))
    session = None if local else _login(args.master, args.user)
    try:
        return args.func(session, args)
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
