"""model_hub — prebuilt trial adapters for external model libraries.

Reference: model_hub/ (HuggingFace Transformers adapters
model_hub/huggingface/, MMDetection model_hub/mmdetection/_trial.py).
Here the HuggingFace adapters: generic PyTorchTrial wrappers around
AutoModelFor* so a config file + a model name (or config) is a runnable
experiment — no trial code to write. On TPU task images they run under
torch-xla via the torch_distributed launch layer; the native JAX path for
transformers remains determined_tpu.models + integrations.transformers
(DetCallback).
"""

from determined_tpu.model_hub.huggingface import (  # noqa: F401
    CausalLMTrial,
    SequenceClassificationTrial,
)
