"""HuggingFace trial adapters (reference model_hub/model_hub/huggingface/:
_trial.py BaseTransformerTrial — re-shaped onto this platform's
PyTorchTrial).

Hyperparameters understood by both adapters:
  model_name          HF hub id or local path (from_pretrained), OR
  model_config        dict of config overrides built offline via
                      AutoConfig/from_config — no network needed
  learning_rate, per_device_batch_size, seq_len
CausalLMTrial extra:  tokens_path (int32 memmap) else synthetic tokens
SequenceClassificationTrial extra: num_labels; synthetic features
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np
import torch

from determined_tpu.pytorch import DataLoader, PyTorchTrial, PyTorchTrialContext


def build_model(hp: Dict[str, Any], auto_cls, config_cls_default: str):
    """model_name → from_pretrained; model_config → offline from_config."""
    import transformers

    if hp.get("model_name"):
        return auto_cls.from_pretrained(hp["model_name"])
    overrides = dict(hp.get("model_config") or {})
    cfg_type = overrides.pop("config_type", config_cls_default)
    cfg_cls = getattr(transformers, cfg_type)
    return auto_cls.from_config(cfg_cls(**overrides))


class _SyntheticTokens(torch.utils.data.Dataset):
    def __init__(self, vocab, seq_len, n=1024, path=None, seed=0):
        self.seq_len = seq_len
        if path:
            self.tokens = np.memmap(path, dtype=np.int32, mode="r")
            self.n = (len(self.tokens) - 1) // seq_len
        else:
            rng = np.random.default_rng(seed)
            self.tokens = rng.integers(
                0, vocab, size=(n * seq_len + 1,)).astype(np.int64)
            self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        chunk = np.asarray(
            self.tokens[i * self.seq_len:(i + 1) * self.seq_len + 1],
            dtype=np.int64)
        return {"input_ids": torch.from_numpy(chunk[:-1]),
                "labels": torch.from_numpy(chunk[1:])}


class CausalLMTrial(PyTorchTrial):
    """Any AutoModelForCausalLM as a runnable trial (reference
    hf_language_modeling adapter)."""

    def __init__(self, context: PyTorchTrialContext):
        super().__init__(context)
        import transformers

        hp = context.get_hparams()
        model = build_model(hp, transformers.AutoModelForCausalLM,
                            "GPT2Config")
        self.vocab = model.config.vocab_size
        self.seq_len = int(hp.get("seq_len", 128))
        self.batch_size = int(hp.get("per_device_batch_size", 8))
        self.tokens_path = hp.get("tokens_path")
        self.n_examples = int(hp.get("synthetic_examples", 1024))
        self.model = context.wrap_model(model)
        self.opt = context.wrap_optimizer(
            torch.optim.AdamW(self.model.parameters(),
                              lr=float(hp.get("learning_rate", 5e-5))))

    def build_training_data_loader(self):
        return DataLoader(
            _SyntheticTokens(self.vocab, self.seq_len, n=self.n_examples,
                             path=self.tokens_path),
            batch_size=self.batch_size)

    def build_validation_data_loader(self):
        return DataLoader(
            _SyntheticTokens(self.vocab, self.seq_len, n=64, seed=7,
                             path=self.tokens_path),
            batch_size=self.batch_size)

    def train_batch(self, batch, epoch_idx, batch_idx):
        out = self.model(input_ids=batch["input_ids"], labels=batch["labels"])
        self.context.backward(out.loss)
        self.context.step_optimizer(self.opt)
        return {"loss": out.loss.item()}

    def evaluate_batch(self, batch, batch_idx):
        with torch.no_grad():
            out = self.model(input_ids=batch["input_ids"],
                             labels=batch["labels"])
        return {"val_loss": out.loss.item()}


class _SyntheticClassification(torch.utils.data.Dataset):
    def __init__(self, vocab, seq_len, num_labels, n=512, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.integers(0, vocab, size=(n, seq_len)).astype(np.int64)
        # learnable rule: label = first token mod num_labels
        self.y = (self.x[:, 0] % num_labels).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"input_ids": torch.from_numpy(self.x[i]),
                "labels": torch.tensor(self.y[i])}


class SequenceClassificationTrial(PyTorchTrial):
    """Any AutoModelForSequenceClassification as a runnable trial
    (reference hf text-classification adapter)."""

    def __init__(self, context: PyTorchTrialContext):
        super().__init__(context)
        import transformers

        hp = context.get_hparams()
        self.num_labels = int(hp.get("num_labels", 2))
        mc = dict(hp.get("model_config") or {})
        mc["num_labels"] = self.num_labels
        hp2 = dict(hp)
        hp2["model_config"] = mc
        model = build_model(
            hp2, transformers.AutoModelForSequenceClassification,
            "BertConfig")
        self.vocab = model.config.vocab_size
        self.seq_len = int(hp.get("seq_len", 32))
        self.batch_size = int(hp.get("per_device_batch_size", 16))
        self.model = context.wrap_model(model)
        self.opt = context.wrap_optimizer(
            torch.optim.AdamW(self.model.parameters(),
                              lr=float(hp.get("learning_rate", 5e-5))))

    def build_training_data_loader(self):
        return DataLoader(
            _SyntheticClassification(self.vocab, self.seq_len,
                                     self.num_labels),
            batch_size=self.batch_size)

    def build_validation_data_loader(self):
        return DataLoader(
            _SyntheticClassification(self.vocab, self.seq_len,
                                     self.num_labels, n=128, seed=7),
            batch_size=self.batch_size)

    def train_batch(self, batch, epoch_idx, batch_idx):
        out = self.model(input_ids=batch["input_ids"], labels=batch["labels"])
        self.context.backward(out.loss)
        self.context.step_optimizer(self.opt)
        return {"loss": out.loss.item()}

    def evaluate_batch(self, batch, batch_idx):
        with torch.no_grad():
            out = self.model(input_ids=batch["input_ids"],
                             labels=batch["labels"])
            acc = (out.logits.argmax(-1) == batch["labels"]).float().mean()
        return {"val_loss": out.loss.item(), "accuracy": acc.item()}
