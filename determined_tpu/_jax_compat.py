"""Shims for jax API drift around ambient meshes.

The codebase targets the jax >= 0.5 ambient-mesh API
(``jax.sharding.set_mesh`` / ``use_mesh`` / ``get_abstract_mesh``). On
older jax (0.4.x) the same mechanism exists only as the physical mesh
context (``with Mesh(...):`` installing
``thread_resources.env.physical_mesh``), under different names.
``install()`` grafts the missing names onto ``jax.sharding`` so every
call site works on both, without pinning jax.

Modules that touch these APIs (train.trainer, ops.ring_attention,
ops.ulysses) call ``install()`` at import; tests get it from conftest.
Idempotent and a no-op on jax versions that already ship the API.
"""

from __future__ import annotations

import contextlib
import contextvars

# True while tracing the body of a shim-wrapped (fully-manual) shard_map —
# sharding constraints naming mesh axes are illegal there, and
# shard_logical consults this to skip them. Always False on jax >= 0.5,
# where the real partial-auto API is used and constraints are legal.
_in_manual_body: contextvars.ContextVar[int] = contextvars.ContextVar(
    "det_jax_compat_in_manual_body", default=0)


def in_manual_shard_map() -> bool:
    return _in_manual_body.get() > 0


def install() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        # Promoted out of jax.experimental in jax 0.5, with a reworked
        # signature: axis_names= replaced auto= (as its complement) and
        # varying-type checking (check_vma=) replaced check_rep=.
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                      check_vma=None, check_rep=None, **kwargs):
            # axis_names ⊂ mesh (partial-auto) is approximated with full
            # manual: old partial-auto lowers axis_index to a PartitionId
            # the SPMD partitioner rejects. Axes the specs don't mention
            # see replicated blocks — numerically identical, redundant
            # compute on those axes. (Full-fidelity partial-auto needs the
            # jax >= 0.5 API, where this wrapper is never installed.)
            if check_rep is None:
                # Replication checking predates (and is stricter than) the
                # vma discipline the call sites are written against.
                check_rep = False

            def body(*args, **kw):
                token = _in_manual_body.set(_in_manual_body.get() + 1)
                try:
                    return f(*args, **kw)
                finally:
                    _in_manual_body.reset(token)

            return _shard_map(body, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep,
                              **kwargs)

        jax.shard_map = shard_map
    if not hasattr(jax, "make_array_from_process_local_data"):
        # Multi-host batch staging (data.prefetch.shard_batch): each
        # process transfers only its local shard of the global batch. On
        # jax builds predating the API, single-process semantics coincide
        # with a plain sharded device_put; true multi-host on such builds
        # would need make_array_from_single_device_arrays, which every
        # supported 0.4.x already has — but so does this API, so the shim
        # only ever serves single-process test environments.
        def make_array_from_process_local_data(sharding, local_data,
                                               global_shape=None):
            if jax.process_count() > 1:  # pragma: no cover — old-jax guard
                raise NotImplementedError(
                    "jax.make_array_from_process_local_data is unavailable "
                    "on this jax build; multi-host prefetch needs jax >= "
                    "0.4.26")
            return jax.device_put(local_data, sharding)

        jax.make_array_from_process_local_data = (
            make_array_from_process_local_data)
    if not hasattr(jax.lax, "pcast"):
        # pcast only casts between varying/invariant *types*; without the
        # vma type system it is the identity on values.
        jax.lax.pcast = lambda x, axis_name=None, *, to=None: x

    sh = jax.sharding
    if not hasattr(sh, "set_mesh"):

        @contextlib.contextmanager
        def set_mesh(mesh):
            # jax < 0.5: entering the physical mesh is what installs the
            # ambient mesh consulted by bare-PartitionSpec sharding
            # constraints and by get_abstract_mesh below.
            with mesh:
                yield mesh

        sh.set_mesh = set_mesh
    if not hasattr(sh, "use_mesh"):
        sh.use_mesh = sh.set_mesh
    if not hasattr(sh, "get_abstract_mesh"):

        def get_abstract_mesh():
            from jax._src import mesh as mesh_lib

            # The physical mesh stands in for the abstract one; callers
            # only consult .empty and .shape, which both carry.
            return mesh_lib.thread_resources.env.physical_mesh

        sh.get_abstract_mesh = get_abstract_mesh

    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # pallas not in this build
        return
    if not hasattr(pltpu, "force_tpu_interpret_mode"):

        @contextlib.contextmanager
        def force_tpu_interpret_mode():
            # Older pallas has no global switch, only the per-call
            # `interpret=` flag; flip its default for the scope.
            orig = pl.pallas_call

            def _interpreted(*args, **kwargs):
                kwargs.setdefault("interpret", True)
                return orig(*args, **kwargs)

            pl.pallas_call = _interpreted
            try:
                yield
            finally:
                pl.pallas_call = orig

        pltpu.force_tpu_interpret_mode = force_tpu_interpret_mode
