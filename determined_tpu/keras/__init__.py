"""Keras trial API.

Reference: harness/determined/keras/ (~3.1k LoC) — TFKerasTrial
(_tf_keras_trial.py:975), a class API where the user builds a compiled
model + data, and the controller (:171) drives fit/evaluate per searcher op
with a callback reporting to the platform.

TPU stance: the reference's Keras path is TF + Horovod only
(_tf_keras_trial.py:284-286). Here the trial targets **Keras 3**, whose JAX
backend runs natively on TPU through the same XLA stack as the rest of this
framework — set ``KERAS_BACKEND=jax`` in the task environment (the image
default). TF-backend models keep working unchanged on CPU hosts.
"""

from determined_tpu.keras._trial import (  # noqa: F401
    DeterminedCallback,
    KerasTrial,
    KerasTrialContext,
    Trainer,
)

# Back-compat alias matching the reference class name.
TFKerasTrial = KerasTrial
