"""KerasTrial + controller (reference _tf_keras_trial.py:975, :171)."""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

from determined_tpu import core

logger = logging.getLogger("determined_tpu.keras")


class KerasTrial:
    """User subclass surface (reference TFKerasTrial):

    - build_model() -> compiled keras.Model
    - build_training_data() -> (x, y) | tf.data.Dataset | keras Dataset
    - build_validation_data() -> same
    """

    def __init__(self, context: "KerasTrialContext"):
        self.context = context

    def build_model(self):
        raise NotImplementedError

    def build_training_data(self):
        raise NotImplementedError

    def build_validation_data(self):
        raise NotImplementedError

    def batch_size(self) -> int:
        return int(self.context.get_hparam_or("global_batch_size", 32))


class KerasTrialContext:
    def __init__(self, core_context: Optional[core.Context] = None,
                 hparams: Optional[Dict[str, Any]] = None):
        self._core = core_context
        self.hparams = hparams or (core_context.hparams if core_context else {})

    def get_hparam(self, name: str) -> Any:
        return self.hparams[name]

    def get_hparam_or(self, name: str, default: Any) -> Any:
        return self.hparams.get(name, default)

    def wrap_model(self, model):
        # The reference wraps for Horovod (:483); Keras-3/JAX needs no
        # wrapper — jax.jit + donated state is built into model.fit.
        return model

    def wrap_optimizer(self, optimizer):
        return optimizer


class DeterminedCallback:
    """keras.callbacks.Callback reporting to the Core API (reference
    keras/callbacks.py). Constructed lazily so importing this module does
    not import keras."""

    def __new__(cls, core_context: core.Context, initial_step: int = 0):
        import keras

        class _Callback(keras.callbacks.Callback):
            def __init__(self) -> None:
                super().__init__()
                self.core = core_context
                self.steps = initial_step
                self.stopped = False

            def on_train_batch_end(self, batch, logs=None):
                self.steps += 1
                if logs and self.steps % 10 == 0:
                    self.core.train.report_training_metrics(self.steps, dict(logs))
                if self.core.preempt.should_preempt():
                    self.model.stop_training = True
                    self.stopped = True

            def on_epoch_end(self, epoch, logs=None):
                if logs:
                    val = {k[4:]: v for k, v in logs.items()
                           if k.startswith("val_")}
                    if val:
                        self.core.train.report_validation_metrics(self.steps, val)

        return _Callback()


class Trainer:
    """Searcher-driven controller for KerasTrial (reference
    TFKerasTrialController :171)."""

    def __init__(self, trial: KerasTrial,
                 core_context: Optional[core.Context] = None):
        self.trial = trial
        self.core = core_context or trial.context._core or core.init(max_length=1)
        self.model = trial.build_model()

    def _save(self, steps: int) -> None:
        with self.core.checkpoint.store_path(
            {"steps_completed": steps, "framework": "keras"}
        ) as (path, _sid):
            self.model.save(os.path.join(path, "model.keras"))

    def _restore(self) -> int:
        latest = self.core.latest_checkpoint
        if not latest:
            return 0
        import keras

        with self.core.checkpoint.restore_path(latest) as path:
            self.model = keras.saving.load_model(os.path.join(path, "model.keras"))
            meta = self.core.checkpoint.load_metadata(latest)
        steps = int(meta.get("steps_completed", 0))
        logger.info("restored keras model at step %d", steps)
        return steps

    def fit(self, searcher_metric: Optional[str] = None) -> int:
        """Train per searcher op; op length is in BATCHES (scheduling_unit
        granularity, like the reference's batches-based ops)."""
        steps = self._restore()
        x_train = self.trial.build_training_data()
        x_val = self.trial.build_validation_data()
        callback = DeterminedCallback(self.core, initial_step=steps)

        for op in self.core.searcher.operations():
            while steps < op.length and not callback.stopped:
                take = op.length - steps
                args: Dict[str, Any] = {
                    "steps_per_epoch": take,
                    "epochs": 1,
                    "callbacks": [callback],
                    "verbose": 0,
                }
                if isinstance(x_train, tuple):
                    self.model.fit(
                        x_train[0], x_train[1],
                        batch_size=self.trial.batch_size(), **args,
                    )
                else:
                    self.model.fit(x_train, **args)
                steps = callback.steps
            if callback.stopped:  # preempted
                self._save(steps)
                return steps
            results = self._evaluate(x_val)
            self.core.train.report_validation_metrics(steps, results)
            metric_name = searcher_metric or self._configured_metric()
            if metric_name is not None and metric_name not in results:
                raise KeyError(
                    f"searcher metric {metric_name!r} not in evaluate() "
                    f"results {sorted(results)}; reporting a wrong metric "
                    "would corrupt the search"
                )
            if metric_name is None:
                metric_name = next(iter(results), None)
            op.report_completed(float(results.get(metric_name, 0.0)))
            self._save(steps)
        return steps

    def _configured_metric(self) -> Optional[str]:
        info = self.core.info
        if info and info.trial:
            return info.trial.config.get("searcher", {}).get("metric")
        return None

    def _evaluate(self, x_val) -> Dict[str, float]:
        if isinstance(x_val, tuple):
            results = self.model.evaluate(
                x_val[0], x_val[1], batch_size=self.trial.batch_size(),
                return_dict=True, verbose=0,
            )
        else:
            results = self.model.evaluate(x_val, return_dict=True, verbose=0)
        return {k: float(v) for k, v in results.items()}
