"""KerasTrial + controller (reference _tf_keras_trial.py:975, :171)."""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

from determined_tpu import core

logger = logging.getLogger("determined_tpu.keras")


class KerasTrial:
    """User subclass surface (reference TFKerasTrial):

    - build_model() -> compiled keras.Model
    - build_training_data() -> (x, y) | tf.data.Dataset | keras Dataset
    - build_validation_data() -> same

    Distribution: the reference's TFKerasTrial is distributed only via
    Horovod (_tf_keras_trial.py:183-186); here Keras 3 on the JAX backend
    distributes over the allocation's chips natively — `mesh_config()`
    (read from the `mesh` hparam block, same home as JaxTrial) selects
    DataParallel, or ModelParallel when fsdp/tensor axes are > 1 (then
    `layout_map()` must describe the weight shardings).
    """

    def __init__(self, context: "KerasTrialContext"):
        self.context = context

    def build_model(self):
        raise NotImplementedError

    def build_training_data(self):
        raise NotImplementedError

    def build_validation_data(self):
        raise NotImplementedError

    def batch_size(self) -> int:
        return int(self.context.get_hparam_or("global_batch_size", 32))

    def mesh_config(self):
        from determined_tpu.parallel.mesh import MeshConfig

        mc = self.context.hparams.get("mesh")
        return MeshConfig.from_dict(mc) if mc else MeshConfig()

    def layout_map(self, device_mesh):
        """For ModelParallel (fsdp/tensor > 1): return a
        keras.distribution.LayoutMap over `device_mesh` mapping weight-path
        regexes to shardings along the "model" mesh dim. Required when the
        mesh requests model axes — the Trainer rejects the mesh otherwise
        (no silent replication)."""
        return None


class KerasTrialContext:
    def __init__(self, core_context: Optional[core.Context] = None,
                 hparams: Optional[Dict[str, Any]] = None):
        self._core = core_context
        self.hparams = hparams or (core_context.hparams if core_context else {})

    def get_hparam(self, name: str) -> Any:
        return self.hparams[name]

    def get_hparam_or(self, name: str, default: Any) -> Any:
        return self.hparams.get(name, default)

    def wrap_model(self, model):
        # The reference wraps for Horovod (:483); Keras-3/JAX needs no
        # wrapper — jax.jit + donated state is built into model.fit.
        return model

    def wrap_optimizer(self, optimizer):
        return optimizer


class DeterminedCallback:
    """keras.callbacks.Callback reporting to the Core API (reference
    keras/callbacks.py). Constructed lazily so importing this module does
    not import keras."""

    def __new__(cls, core_context: core.Context, initial_step: int = 0):
        import keras

        class _Callback(keras.callbacks.Callback):
            def __init__(self) -> None:
                super().__init__()
                self.core = core_context
                self.steps = initial_step
                self.stopped = False

            def on_train_batch_end(self, batch, logs=None):
                self.steps += 1
                if logs and self.steps % 10 == 0:
                    self.core.train.report_training_metrics(self.steps, dict(logs))
                if self.core.preempt.should_preempt():
                    self.model.stop_training = True
                    self.stopped = True

            def on_epoch_end(self, epoch, logs=None):
                if logs:
                    val = {k[4:]: v for k, v in logs.items()
                           if k.startswith("val_")}
                    if val:
                        self.core.train.report_validation_metrics(self.steps, val)

        return _Callback()


def build_distribution(trial: KerasTrial):
    """Map the trial's MeshConfig onto a keras.distribution strategy.

    data-only mesh    -> DataParallel over all devices
    fsdp/tensor > 1   -> ModelParallel on a ("batch", "model") DeviceMesh
                         with the trial's layout_map (required)
    single device     -> None
    """
    import keras

    devices = keras.distribution.list_devices()
    cfg = trial.mesh_config().resolve(len(devices))
    if cfg.pipeline > 1 or cfg.context > 1 or cfg.expert > 1:
        raise ValueError(
            "KerasTrial supports data/fsdp/tensor mesh axes only "
            f"(got {cfg}); use the JaxTrial API for pipeline/context/expert"
        )
    model_par = cfg.fsdp * cfg.tensor
    if model_par > 1:
        mesh = keras.distribution.DeviceMesh(
            shape=(cfg.data, model_par),
            axis_names=("batch", "model"),
            devices=devices,
        )
        lm = trial.layout_map(mesh)
        if lm is None:
            raise ValueError(
                f"mesh requests {model_par}-way model parallelism but "
                f"{type(trial).__name__}.layout_map() returned None; "
                "return a keras.distribution.LayoutMap describing the "
                "weight shardings (or use a data-only mesh)"
            )
        return keras.distribution.ModelParallel(
            layout_map=lm, batch_dim_name="batch"
        )
    if len(devices) > 1:
        return keras.distribution.DataParallel(devices=devices)
    return None


class Trainer:
    """Searcher-driven controller for KerasTrial (reference
    TFKerasTrialController :171). Distribution is installed BEFORE
    build_model so variables are created already sharded."""

    def __init__(self, trial: KerasTrial,
                 core_context: Optional[core.Context] = None):
        self.trial = trial
        self.core = core_context or trial.context._core or core.init(max_length=1)
        self.distribution = build_distribution(trial)
        if self.distribution is not None:
            import keras

            keras.distribution.set_distribution(self.distribution)
            logger.info("keras distribution: %s",
                        type(self.distribution).__name__)
        self.model = trial.build_model()

    def _save(self, steps: int) -> None:
        with self.core.checkpoint.store_path(
            {"steps_completed": steps, "framework": "keras"}
        ) as (path, _sid):
            self.model.save(os.path.join(path, "model.keras"))

    def _restore(self) -> int:
        latest = self.core.latest_checkpoint
        if not latest:
            return 0
        import keras

        with self.core.checkpoint.restore_path(latest) as path:
            self.model = keras.saving.load_model(os.path.join(path, "model.keras"))
            meta = self.core.checkpoint.load_metadata(latest)
        steps = int(meta.get("steps_completed", 0))
        logger.info("restored keras model at step %d", steps)
        return steps

    def fit(self, searcher_metric: Optional[str] = None) -> int:
        """Train per searcher op; op length is in BATCHES (scheduling_unit
        granularity, like the reference's batches-based ops)."""
        steps = self._restore()
        x_train = self.trial.build_training_data()
        x_val = self.trial.build_validation_data()
        callback = DeterminedCallback(self.core, initial_step=steps)

        for op in self.core.searcher.operations():
            while steps < op.length and not callback.stopped:
                take = op.length - steps
                args: Dict[str, Any] = {
                    "steps_per_epoch": take,
                    "epochs": 1,
                    "callbacks": [callback],
                    "verbose": 0,
                }
                if isinstance(x_train, tuple):
                    self.model.fit(
                        x_train[0], x_train[1],
                        batch_size=self.trial.batch_size(), **args,
                    )
                else:
                    self.model.fit(x_train, **args)
                steps = callback.steps
            if callback.stopped:  # preempted
                self._save(steps)
                return steps
            results = self._evaluate(x_val)
            self.core.train.report_validation_metrics(steps, results)
            metric_name = searcher_metric or self._configured_metric()
            if metric_name is not None and metric_name not in results:
                raise KeyError(
                    f"searcher metric {metric_name!r} not in evaluate() "
                    f"results {sorted(results)}; reporting a wrong metric "
                    "would corrupt the search"
                )
            if metric_name is None:
                metric_name = next(iter(results), None)
            op.report_completed(float(results.get(metric_name, 0.0)))
            self._save(steps)
        return steps

    def _configured_metric(self) -> Optional[str]:
        info = self.core.info
        if info and info.trial:
            return info.trial.config.get("searcher", {}).get("metric")
        return None

    def _evaluate(self, x_val) -> Dict[str, float]:
        if isinstance(x_val, tuple):
            results = self.model.evaluate(
                x_val[0], x_val[1], batch_size=self.trial.batch_size(),
                return_dict=True, verbose=0,
            )
        else:
            results = self.model.evaluate(x_val, return_dict=True, verbose=0)
        return {k: float(v) for k, v in results.items()}
