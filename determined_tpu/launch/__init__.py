"""Launch layers — distributed process fan-out run as the task entrypoint
(reference harness/determined/launch/: torch_distributed.py, horovod.py,
deepspeed.py).

On TPU the native JAX path needs no fan-out (one process per host owns all
local chips), so the only launcher is for the PyTorch compat trial API:
`python -m determined_tpu.launch.torch_distributed -- python3 train.py`.
"""
