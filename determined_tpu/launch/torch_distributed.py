"""torch.distributed launch layer for PyTorchTrial.

Reference: harness/determined/launch/torch_distributed.py:74 — wraps the
user script in `torch.distributed.run --nnodes ... --node_rank ...
--master_addr <chief>`. The TPU-native rewrite spawns the worker processes
directly (no torchrun dependency) and wires the rendezvous from the
master-provided env:

  nnodes     = DET_NUM_NODES      (hosts in the allocation)
  node_rank  = DET_NODE_RANK
  chief addr = DET_CHIEF_IP       (master rendezvous)
  nproc      = --nproc-per-node | auto:
                 torch-xla present  -> 1 process per host (a torch-xla
                   process owns all local chips via xla:// — unlike GPU's
                   process-per-device)
                 else               -> DET_NPROC_PER_NODE or 1

Each worker gets the standard torch.distributed env contract (RANK,
WORLD_SIZE, LOCAL_RANK, LOCAL_WORLD_SIZE, MASTER_ADDR, MASTER_PORT) plus
DET_TORCH_BACKEND (xla|gloo|nccl) so PyTorchTrial's Trainer knows how to
init the process group. stdout/stderr are prefixed with the global rank
(reference launch/wrap_rank.py).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
from typing import List, Optional


def _has_torch_xla() -> bool:
    import importlib.util

    return importlib.util.find_spec("torch_xla") is not None


def pick_backend() -> str:
    if _has_torch_xla():
        return "xla"
    import torch

    return "nccl" if torch.cuda.is_available() else "gloo"


def worker_env(
    base_env: dict,
    *,
    node_rank: int,
    nnodes: int,
    local_rank: int,
    nproc_per_node: int,
    master_addr: str,
    master_port: int,
    backend: str,
) -> dict:
    env = dict(base_env)
    env.update(
        RANK=str(node_rank * nproc_per_node + local_rank),
        WORLD_SIZE=str(nnodes * nproc_per_node),
        LOCAL_RANK=str(local_rank),
        LOCAL_WORLD_SIZE=str(nproc_per_node),
        MASTER_ADDR=master_addr,
        MASTER_PORT=str(master_port),
        DET_TORCH_BACKEND=backend,
    )
    return env


def _stream_prefixed(pipe, rank: int, out) -> None:
    # reference launch/wrap_rank.py — prefix each line with the global rank
    for line in iter(pipe.readline, b""):
        out.write(f"[rank={rank}] ".encode() + line)
        out.flush()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    nproc = 0
    if argv and argv[0] == "--nproc-per-node":
        nproc = int(argv[1])
        argv = argv[2:]
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("usage: torch_distributed [--nproc-per-node N] -- cmd ...",
              file=sys.stderr)
        return 2

    backend = pick_backend()
    if nproc <= 0:
        if backend == "xla":
            nproc = 1  # one torch-xla process per host owns all local chips
        else:
            nproc = int(os.environ.get("DET_NPROC_PER_NODE", "1"))

    node_rank = int(os.environ.get("DET_NODE_RANK", "0"))
    nnodes = int(os.environ.get("DET_NUM_NODES", "1"))
    chief = os.environ.get("DET_CHIEF_IP", "127.0.0.1")
    port = int(os.environ.get("DET_TORCH_MASTER_PORT", "29400"))

    procs: List[subprocess.Popen] = []
    streams: List[threading.Thread] = []
    for local_rank in range(nproc):
        env = worker_env(
            os.environ.copy(),
            node_rank=node_rank,
            nnodes=nnodes,
            local_rank=local_rank,
            nproc_per_node=nproc,
            master_addr=chief,
            master_port=port,
            backend=backend,
        )
        p = subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT
        )
        rank = node_rank * nproc + local_rank
        t = threading.Thread(
            target=_stream_prefixed, args=(p.stdout, rank, sys.stdout.buffer),
            daemon=True,
        )
        t.start()
        procs.append(p)
        streams.append(t)

    def forward(signum, frame):
        for p in procs:
            try:
                p.send_signal(signum)
            except ProcessLookupError:
                pass

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)

    # Monitor-and-kill (torchrun semantics): the first worker to die with a
    # non-zero status takes the rest down — survivors would otherwise hang
    # in collectives (gloo barrier default timeout is 30 min).
    import time

    rc = 0
    alive = list(procs)
    while alive:
        for p in list(alive):
            code = p.poll()
            if code is None:
                continue
            alive.remove(p)
            if code != 0 and rc == 0:
                rc = code
                print(
                    f"worker pid={p.pid} exited {code}; terminating "
                    f"{len(alive)} remaining worker(s)",
                    file=sys.stderr,
                )
                for q in alive:
                    try:
                        q.terminate()
                    except ProcessLookupError:
                        pass
        if alive:
            time.sleep(0.2)
    for t in streams:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    sys.exit(main())
