"""Autotune — the TPU analogue of DeepSpeed Autotune (dsat).

Reference: harness/determined/pytorch/dsat/_dsat_search_method.py — a
custom-searcher workflow that profiles a model then searches deployment
knobs (ZeRO stage, micro-batch size) for throughput. On TPU the knobs that
matter are the per-chip batch size and rematerialisation: bigger batches
amortize HBM bandwidth until they OOM; remat trades FLOPs for memory and
changes where that cliff sits.

`BatchSizeSearchMethod` drives trials through the custom-searcher API:
doubling the global batch size until a trial fails (the OOM cliff), then
narrowing with a binary search between the last good and first bad size,
ranking survivors by reported throughput (searcher metric
`samples_per_second`, larger is better).
"""

from determined_tpu.autotune._batch_size import (  # noqa: F401
    BatchSizeSearchMethod,
)
