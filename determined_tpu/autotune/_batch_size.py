"""Batch-size autotune search method (reference dsat
_dsat_search_method.py: DSATTrialTracker :169, BinarySearchDSATSearchMethod
:965 — re-derived for the TPU knob space)."""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from determined_tpu.searcher import (
    Close,
    Create,
    Operation,
    SearchMethod,
    Shutdown,
    ValidateAfter,
)

logger = logging.getLogger("determined_tpu.autotune")


class BatchSizeSearchMethod(SearchMethod):
    """Find the highest-throughput global batch size.

    Phase 1 (cliff hunt): trials at start_size, 2x, 4x, ... run
    `profile_steps` batches each and report samples_per_second; the first
    failure (OOM kills the trial -> exited_early) bounds the search.
    Phase 2 (binary search): midpoints between the last good and first bad
    size until the window is tight.

    The winner is the size with the best throughput; `best()` returns
    (batch_size, samples_per_second). Extra hparams (e.g. {"remat": True})
    pass through to every trial.
    """

    def __init__(
        self,
        start_size: int = 8,
        max_size: int = 4096,
        profile_steps: int = 20,
        base_hparams: Optional[Dict[str, Any]] = None,
        window_factor: float = 1.25,
    ):
        self.start_size = start_size
        self.max_size = max_size
        self.profile_steps = profile_steps
        self.base_hparams = dict(base_hparams or {})
        self.window_factor = window_factor

        self.results: Dict[int, float] = {}  # size -> samples/sec
        self.failed_sizes: List[int] = []
        self._inflight: Dict[str, int] = {}  # request_id -> size
        self._good_bound = 0
        self._bad_bound: Optional[int] = None
        self._retried: set = set()  # sizes given a second chance
        self._done = False

    # -- search driver -------------------------------------------------

    def _launch(self, size: int) -> List[Operation]:
        hp = dict(self.base_hparams)
        hp["global_batch_size"] = size
        create = Create(hparams=hp)
        self._inflight[create.request_id] = size
        logger.info("autotune: trying global_batch_size=%d", size)
        return [create, ValidateAfter(create.request_id, self.profile_steps)]

    def _next_size(self) -> Optional[int]:
        if self._bad_bound is None:
            # cliff hunt: keep doubling
            nxt = self._good_bound * 2 if self._good_bound else self.start_size
            return nxt if nxt <= self.max_size else None
        # binary search inside (good, bad)
        lo, hi = self._good_bound, self._bad_bound
        if lo == 0:  # even start_size failed
            return None
        mid = (lo + hi) // 2
        if mid <= lo or hi <= lo * self.window_factor:
            return None  # window tight enough
        return mid

    def _advance(self) -> List[Operation]:
        if self._inflight:
            return []
        nxt = self._next_size()
        if nxt is None:
            self._done = True
            if self.results:
                size, sps = self.best()
                logger.info(
                    "autotune: best global_batch_size=%d (%.1f samples/s)",
                    size, sps)
            return [Shutdown()]
        return self._launch(nxt)

    # -- SearchMethod interface ---------------------------------------

    def initial_operations(self) -> List[Operation]:
        return self._launch(self.start_size)

    def on_validation_completed(self, request_id: str, metric: float,
                                train_length: int) -> List[Operation]:
        size = self._inflight.get(request_id)
        if size is None:
            return []
        # metric = samples_per_second (larger is better; the experiment
        # config must set searcher.smaller_is_better: false)
        self.results[size] = metric
        self._good_bound = max(self._good_bound, size)
        return [Close(request_id)]

    def on_trial_closed(self, request_id: str) -> List[Operation]:
        self._inflight.pop(request_id, None)
        return self._advance()

    def on_trial_exited_early(self, request_id: str,
                              reason: str) -> List[Operation]:
        size = self._inflight.pop(request_id, None)
        if size is None:
            return self._advance()
        if reason == "user_canceled":
            # Not a memory signal — stop the search cleanly.
            self._done = True
            return [Shutdown(cancel=True)]
        logger.info("autotune: global_batch_size=%d failed (%s)",
                    size, reason)
        # A crash is not necessarily OOM (flaky node, preemption): give
        # each size ONE retry before treating it as the memory cliff —
        # a mis-set bad bound would converge on a far-too-small batch.
        if size not in self._retried:
            self._retried.add(size)
            return self._launch(size)
        self.failed_sizes.append(size)
        if self._bad_bound is None or size < self._bad_bound:
            self._bad_bound = size
        return self._advance()

    def progress(self) -> float:
        if self._done:
            return 1.0
        if self._bad_bound is None:
            return min(0.5, 0.1 * len(self.results))
        return 0.5 + 0.5 * min(1.0, len(self.results) / 6.0)

    def best(self) -> tuple:
        size = max(self.results, key=lambda s: self.results[s])
        return size, self.results[size]
