"""A/B harness for the input pipeline: synchronous feed vs DevicePrefetcher.

Shared by `bench.py --only input`, `bench_resnet.py` (detail block) and the
tier-1 acceptance test (tests/test_data_pipeline.py): drive the SAME host
iterator and per-step consumer through both paths and report steady-state
step times + input-wait means, so the "prefetch moves H2D off the critical
path" claim is a measured number, not a comment.

`input_wait_ms` means the same thing on both sides: wall time the step loop
spends obtaining a ready (device-resident, when sharded) batch — host
iterator + H2D inline for the synchronous path, queue wait for the
prefetched path.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable

from determined_tpu.data.prefetch import DevicePrefetcher


def _consume(it: Iterable[Any], step_fn: Callable[[Any], None],
             sync_put: Any = None) -> Dict[str, float]:
    """Run step_fn over every batch, timing how long each batch took to
    obtain. sync_put: a sharding the synchronous path device_puts + blocks
    with inline — what the unprefetched trainer loop pays per step."""
    import jax

    it = iter(it)
    n = 0
    wait_ms = 0.0
    t0 = time.perf_counter()
    while True:
        w0 = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            break
        if sync_put is not None:
            batch = jax.device_put(batch, sync_put)
            jax.block_until_ready(batch)
        wait_ms += (time.perf_counter() - w0) * 1e3
        step_fn(batch)
        n += 1
    dt = time.perf_counter() - t0
    return {"steps": n, "total_s": round(dt, 4),
            "step_ms": round(dt / n * 1e3, 3) if n else 0.0,
            "input_wait_ms": round(wait_ms / n, 3) if n else 0.0}


def ab_compare(
    make_iter: Callable[[], Iterable[Any]],
    step_fn: Callable[[Any], None],
    sharding: Any = None,
    depth: int = 2,
) -> Dict[str, Any]:
    """Run the same workload synchronously and prefetched; return both
    sides plus the speedup. `make_iter` must return a fresh, identically-
    ordered finite iterable each call."""
    sync = _consume(make_iter(), step_fn, sync_put=sharding)

    pf = DevicePrefetcher(make_iter(), sharding=sharding, depth=depth,
                          name="bench")
    try:
        prefetched = _consume(pf, step_fn)
        h2d = pf.window_metrics().get("h2d_ms")
        if h2d is not None:
            prefetched["h2d_ms"] = round(h2d, 3)
    finally:
        pf.close()

    speedup = (sync["step_ms"] / prefetched["step_ms"]
               if prefetched["step_ms"] else 0.0)
    return {
        "sync": sync,
        "prefetch": prefetched,
        "speedup": round(speedup, 3),
        "input_wait_ms_delta": round(
            sync["input_wait_ms"] - prefetched["input_wait_ms"], 3),
        "depth": depth,
    }
