"""determined_tpu.data — the async input pipeline.

Keeps the host ahead of the accelerator: batches are pulled, sharded and
transferred to HBM by a background thread so the jitted step never waits on
host preprocessing or the H2D copy (see prefetch.py for the full design).
The Trainer wires this in by default; trials opt out via the `prefetch:`
expconf block or a `prefetch = False` trial attribute.
"""

from determined_tpu.data.prefetch import (  # noqa: F401
    FAULT_POINT_QUEUE,
    DevicePrefetcher,
    PrefetchConfig,
    shard_batch,
)
