"""Async input pipeline: device-prefetch, double-buffered batches.

The trainer's hot loop is fully async on the device side (one jit per
trial, batched metric fetches), but a synchronous `next(data_iter)` puts
host preprocessing + the H2D copy on the step critical path — exactly the
tf.data prefetch-to-device problem (Murray et al.) and the Pathways rule
that the host must always run ahead of the accelerator.

`DevicePrefetcher` wraps any trial's `build_training_data()` /
`build_validation_data()` iterable:

  - a background thread pulls host batches into a bounded queue
    (configurable depth; default 2 = double buffering),
  - each batch is sharded with the mesh's batch `NamedSharding` via
    `jax.device_put` and blocked-until-ready *in the producer thread*, so
    the batch is resident on HBM — the H2D copy overlaps the previous
    step's compute instead of serializing with it,
  - multi-host processes go through
    `jax.make_array_from_process_local_data` (behind the `_jax_compat`
    shim) so each host transfers only its local shard,
  - iterator exceptions are re-raised in the consumer (after any batches
    queued before the failure — order preserved), and `close()` tears the
    thread down deterministically on preemption / op boundaries,
  - per-step `input_wait_ms` / `h2d_ms` / queue-depth gauges accumulate in
    a window the Trainer drains at report boundaries, so an input-bound
    trial is visible in metrics instead of masquerading as slow TPU time.

Chaos: the producer honors the `data.prefetch.queue` fault point
(`DET_FAULTS=data.prefetch.queue:error` etc. — docs/chaos.md).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import Any, Dict, Iterable, Iterator, Optional, Tuple

from determined_tpu.common import faultpoint

logger = logging.getLogger("determined_tpu.data")

FAULT_POINT_QUEUE = "data.prefetch.queue"

_SENTINEL = object()


@dataclasses.dataclass
class PrefetchConfig:
    """Resolved prefetch knobs (trial attribute over expconf block).

    expconf block (validated by `expconf.validate`)::

        prefetch:
          enabled: true     # opt-out switch; prefetch is ON by default
          depth: 2          # queue depth; 2 = double buffering
          shard: true       # device_put with the mesh batch sharding

    A trial can override per-trial with `prefetch = False` (opt out) or
    `prefetch = {"depth": 4}` (see JaxTrial.prefetch).
    """

    enabled: bool = True
    depth: int = 2
    shard: bool = True

    @classmethod
    def from_block(cls, block: Any) -> "PrefetchConfig":
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        if isinstance(block, dict):
            return cls(
                enabled=bool(block.get("enabled", True)),
                depth=max(1, int(block.get("depth", 2))),
                shard=bool(block.get("shard", True)),
            )
        raise TypeError(f"prefetch config must be a bool or mapping, got "
                        f"{type(block).__name__}")

    @classmethod
    def resolve(cls, trial: Any = None,
                expconf: Optional[Dict[str, Any]] = None) -> "PrefetchConfig":
        """Trial attribute wins over the experiment config block; both
        default to enabled (the opt-*out* contract)."""
        trial_attr = getattr(trial, "prefetch", None)
        if trial_attr is not None:
            return cls.from_block(trial_attr)
        if isinstance(expconf, dict) and expconf.get("prefetch") is not None:
            return cls.from_block(expconf.get("prefetch"))
        return cls()


def shard_batch(batch: Any, sharding) -> Any:
    """Device-put a host batch with the mesh's batch sharding.

    `sharding` is either a single `Sharding` applied to every leaf or a
    pytree of per-leaf shardings (the jitted step's exact input
    `NamedSharding`s — `train.step.step_input_shardings` — so batches
    arrive already in the step's declared in_shardings and XLA inserts no
    resharding copy on the hot path).

    Single-process: one `jax.device_put` over the whole pytree (non-blocking
    dispatch). Multi-host: per-leaf `make_array_from_process_local_data`, so
    each process transfers only its local shard of the global batch.
    """
    import jax
    import numpy as np
    from jax.sharding import Sharding

    if jax.process_count() > 1:
        if isinstance(sharding, Sharding):
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    sharding, np.asarray(x)),
                batch,
            )
        return jax.tree_util.tree_map(
            lambda x, s: jax.make_array_from_process_local_data(
                s, np.asarray(x)),
            batch, sharding,
        )
    return jax.device_put(batch, sharding)


class DevicePrefetcher:
    """Iterator: background thread stages device-resident batches.

    Wraps `iterable` (consumed exactly once, in order). When `sharding` is
    given, batches are device_put with it and blocked-until-ready in the
    producer thread before queuing — handing the consumer arrays already on
    HBM. Finite iterables raise StopIteration in the consumer when
    exhausted; producer exceptions re-raise in the consumer after any
    batches queued before the failure.

    Always `close()` (or use as a context manager): it is idempotent,
    unblocks a full queue, and joins the thread, so preemption and
    mid-epoch errors leave no orphaned threads.
    """

    THREAD_PREFIX = "data-prefetch"

    def __init__(
        self,
        iterable: Iterable[Any],
        sharding: Any = None,
        depth: int = 2,
        name: str = "train",
    ):
        self._it: Iterator[Any] = iter(iterable)
        self._sharding = sharding
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._closed = False
        # A batch the producer had fully staged when close()/detach() raced
        # its queue put — detach() hands it back so no batch is ever lost.
        self._overflow: Optional[Any] = None
        # metric window (drained by window_sums at report boundaries)
        self._mlock = threading.Lock()
        self._wait_ms_sum = 0.0
        self._h2d_ms_sum = 0.0
        self._depth_sum = 0.0
        self._n = 0
        self._thread = threading.Thread(
            target=self._produce, daemon=True,
            name=f"{self.THREAD_PREFIX}-{name}")
        self._thread.start()

    # -- producer ------------------------------------------------------

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    batch = next(self._it)
                except StopIteration:
                    break
                action = faultpoint.fire(FAULT_POINT_QUEUE)
                if action is faultpoint.Action.ERROR:
                    raise faultpoint.FaultInjected(FAULT_POINT_QUEUE)
                if action is faultpoint.Action.DROP:
                    continue
                t0 = time.perf_counter()
                if self._sharding is not None:
                    import jax

                    batch = shard_batch(batch, self._sharding)
                    # Block HERE, in the producer: the consumer must find
                    # the batch already resident on HBM, and the wait
                    # overlaps the previous step's compute.
                    jax.block_until_ready(batch)
                h2d_ms = (time.perf_counter() - t0) * 1e3
                if not self._put((batch, h2d_ms)):
                    # Closed/detached while the queue was full: stash the
                    # staged batch so detach() preserves data order.
                    self._overflow = batch
                    return
        except BaseException as e:  # re-raised in the consumer
            self._exc = e
        finally:
            self._put(_SENTINEL)

    def _put(self, item: Any) -> bool:
        """Bounded-queue put that aborts when close() is racing us."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        wait_ms = (time.perf_counter() - t0) * 1e3
        if item is _SENTINEL:
            self._thread.join(timeout=5.0)
            self._closed = True
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        batch, h2d_ms = item
        with self._mlock:
            self._wait_ms_sum += wait_ms
            self._h2d_ms_sum += h2d_ms
            self._depth_sum += self._q.qsize()
            self._n += 1
        return batch

    # -- metrics -------------------------------------------------------

    def window_sums(self) -> Tuple[float, float, float, int]:
        """(input_wait_ms_sum, h2d_ms_sum, queue_depth_sum, n_batches)
        since the last call; resets the window."""
        with self._mlock:
            out = (self._wait_ms_sum, self._h2d_ms_sum, self._depth_sum,
                   self._n)
            self._wait_ms_sum = self._h2d_ms_sum = self._depth_sum = 0.0
            self._n = 0
        return out

    def window_metrics(self) -> Dict[str, float]:
        """Per-batch means for the window ({} when no batches flowed)."""
        wait, h2d, depth, n = self.window_sums()
        if not n:
            return {}
        return {
            "input_wait_ms": wait / n,
            "h2d_ms": h2d / n,
            "prefetch_queue_depth": depth / n,
        }

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        """Idempotent teardown: stop the producer, unblock it if the queue
        is full, join. Safe from preemption / exception paths."""
        if self._closed and not self._thread.is_alive():
            return
        self._closed = True
        self._stop.set()
        while True:  # drain so a blocked _put observes _stop
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            logger.warning(
                "prefetch thread %s did not exit within 5s (host iterator "
                "stuck?); it is a daemon and will not block shutdown",
                self._thread.name)

    def detach(self) -> Tuple[list, Iterator[Any]]:
        """Stop prefetching WITHOUT losing position: returns
        (staged_batches, underlying_iterator) such that chaining the two
        reproduces exactly the stream a continued consumer would have
        seen. Used by elastic resize (docs/elasticity.md) to rebuild the
        pipeline around a new mesh's batch sharding while preserving data
        order; staged batches are device arrays sharded for the OLD mesh —
        re-device_put reshards them.

        The prefetcher is unusable afterwards (a fresh one wraps the
        returned stream)."""
        self._stop.set()
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():
            raise RuntimeError(
                "prefetch producer did not stop; cannot detach without "
                "risking a lost batch")
        staged: list = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                continue
            staged.append(item[0])
        if self._overflow is not None:
            staged.append(self._overflow)
            self._overflow = None
        self._closed = True
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc
        return staged, self._it

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover — belt and braces
        try:
            self.close()
        except Exception:
            pass
