"""DistributedContext — process topology + host-level control-plane collectives.

The reference coordinates non-gradient data (sharded-checkpoint metadata,
metric gathering) chief↔workers over ZMQ (harness/determined/ipc.py:34,
core/_distributed.py:12), and its collectives move arbitrary pickled python
objects. On TPU the data plane is XLA collectives over ICI; for the
*control* plane we ride the same transport jax already maintains:
byte-level allgather/broadcast are built from
`jax.experimental.multihost_utils` (length-prefixed uint8 buffers, padded to
the max length so every host contributes the same shape), and
gather/allgather/broadcast pickle arbitrary objects on top — dicts, strings,
file-metadata lists, whatever the checkpoint layer needs.

Transports:
  - `_JaxTransport`   — production multi-host path over jax.distributed.
  - `_ThreadTransport`— threads-as-hosts, for tests and local simulation
    (the TPU analogue of the reference's harness/tests/parallel.py
    `parallel.Execution` ZMQ-over-localhost harness).

Topology model (one process per TPU-VM host, owning all local chips — unlike
the reference's process-per-GPU):
  rank        — this process's index in the allocation (== TPU worker id)
  size        — number of processes (hosts)
  local_devices / global device count come from jax itself.
"""

from __future__ import annotations

import dataclasses
import pickle
import threading
from typing import Any, List, Optional


class _JaxTransport:
    """Byte collectives over multihost_utils (jax.distributed client)."""

    def allgather_bytes(self, payload: bytes) -> List[bytes]:
        import numpy as np
        from jax.experimental import multihost_utils

        n = np.asarray(len(payload), np.int64)
        lengths = np.asarray(multihost_utils.process_allgather(n)).reshape(-1)
        maxlen = max(1, int(lengths.max()))
        buf = np.zeros(maxlen, np.uint8)
        buf[: len(payload)] = np.frombuffer(payload, np.uint8)
        gathered = np.asarray(multihost_utils.process_allgather(buf))
        # Older jax returns the lone buffer un-stacked in single-process
        # runs; normalize to [n_processes, maxlen] either way.
        gathered = gathered.reshape(len(lengths), -1)
        return [
            gathered[i, : int(lengths[i])].tobytes() for i in range(len(lengths))
        ]

    def broadcast_bytes(self, payload: bytes, is_source: bool) -> bytes:
        import numpy as np
        from jax.experimental import multihost_utils

        n = multihost_utils.broadcast_one_to_all(
            np.asarray(len(payload) if is_source else 0, np.int64)
        )
        n = int(n)
        buf = np.zeros(max(1, n), np.uint8)
        if is_source:
            buf[:n] = np.frombuffer(payload, np.uint8)
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        return out[:n].tobytes()

    def barrier(self, name: str) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


class _ThreadSharedState:
    def __init__(self, size: int):
        self.size = size
        self.barrier = threading.Barrier(size)
        self.slots: List[Optional[bytes]] = [None] * size
        self.bcast: Optional[bytes] = None


class _ThreadTransport:
    """Threads-as-hosts transport: N threads share one state object.

    Build one per rank via `make_thread_transports(n)`. Double barrier =
    publish / read-before-reuse."""

    def __init__(self, shared: _ThreadSharedState, rank: int):
        self._shared = shared
        self._rank = rank

    def allgather_bytes(self, payload: bytes) -> List[bytes]:
        s = self._shared
        s.slots[self._rank] = payload
        s.barrier.wait()
        out = list(s.slots)  # type: ignore[arg-type]
        s.barrier.wait()
        return out  # type: ignore[return-value]

    def broadcast_bytes(self, payload: bytes, is_source: bool) -> bytes:
        s = self._shared
        if is_source:
            s.bcast = payload
        s.barrier.wait()
        out = s.bcast
        s.barrier.wait()
        assert out is not None
        return out

    def barrier(self, name: str) -> None:
        self._shared.barrier.wait()


def make_thread_transports(size: int) -> List[_ThreadTransport]:
    shared = _ThreadSharedState(size)
    return [_ThreadTransport(shared, r) for r in range(size)]


@dataclasses.dataclass
class DistributedContext:
    rank: int = 0
    size: int = 1
    initialized_jax_distributed: bool = False
    transport: Optional[Any] = None  # byte-level collectives (size>1 only)

    @property
    def is_chief(self) -> bool:
        return self.rank == 0

    # -- constructors -------------------------------------------------

    @classmethod
    def local(cls) -> "DistributedContext":
        return cls(rank=0, size=1)

    @classmethod
    def from_allocation(
        cls,
        coordinator_addr: str,
        num_processes: int,
        process_id: int,
    ) -> "DistributedContext":
        """Multi-host bring-up: master rendezvous supplies coordinator address
        (= chief host) and ranks; we hand them to jax.distributed so every
        host sees the full global device set (SURVEY.md §5 'Distributed
        communication backend')."""
        if num_processes <= 1:
            return cls.local()
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes,
            process_id=process_id,
        )
        return cls(
            rank=process_id,
            size=num_processes,
            initialized_jax_distributed=True,
            transport=_JaxTransport(),
        )

    @classmethod
    def for_test(cls, rank: int, size: int, transport: Any) -> "DistributedContext":
        """Threads-as-hosts context (pair with make_thread_transports)."""
        return cls(rank=rank, size=size, transport=transport)

    # -- control-plane collectives ------------------------------------
    # Arbitrary pickleable objects, like the reference's ZMQ plane
    # (harness/determined/ipc.py:34): dicts, strings, numpy arrays, ...

    def gather(self, obj: Any) -> Optional[List[Any]]:
        """Gather python objects to the chief (None on non-chief ranks)."""
        if self.size == 1:
            return [obj]
        vals = self.allgather(obj)
        return vals if self.is_chief else None

    def allgather(self, obj: Any) -> List[Any]:
        if self.size == 1:
            return [obj]
        payloads = self._t().allgather_bytes(pickle.dumps(obj))
        return [pickle.loads(p) for p in payloads]

    def broadcast(self, obj: Any) -> Any:
        if self.size == 1:
            return obj
        payload = pickle.dumps(obj) if self.is_chief else b""
        return pickle.loads(self._t().broadcast_bytes(payload, self.is_chief))

    def barrier(self, name: str = "barrier") -> None:
        if self.size == 1:
            return
        self._t().barrier(name)

    def _t(self) -> Any:
        if self.transport is None:
            # Multi-host contexts built by from_allocation always carry one;
            # hand-rolled ones default to the jax plane.
            self.transport = _JaxTransport()
        return self.transport

    def shutdown(self) -> None:
        if self.initialized_jax_distributed:
            import jax

            jax.distributed.shutdown()
