"""DistributedContext — process topology + host-level control-plane collectives.

The reference coordinates non-gradient data (sharded-checkpoint metadata,
metric gathering) chief↔workers over ZMQ (harness/determined/ipc.py:34,
core/_distributed.py:12). On TPU the data plane is XLA collectives over ICI,
and for the *control* plane we ride the same transport: small host-level
gather/broadcast are implemented with
`jax.experimental.multihost_utils` (which uses the jax.distributed client) —
no extra socket layer needed. A single-process context is the default for
1-host allocations and local mode.

Topology model (one process per TPU-VM host, owning all local chips — unlike
the reference's process-per-GPU):
  rank        — this process's index in the allocation (== TPU worker id)
  size        — number of processes (hosts)
  local_devices / global device count come from jax itself.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional


@dataclasses.dataclass
class DistributedContext:
    rank: int = 0
    size: int = 1
    initialized_jax_distributed: bool = False

    @property
    def is_chief(self) -> bool:
        return self.rank == 0

    # -- constructors -------------------------------------------------

    @classmethod
    def local(cls) -> "DistributedContext":
        return cls(rank=0, size=1)

    @classmethod
    def from_allocation(
        cls,
        coordinator_addr: str,
        num_processes: int,
        process_id: int,
    ) -> "DistributedContext":
        """Multi-host bring-up: master rendezvous supplies coordinator address
        (= chief host) and ranks; we hand them to jax.distributed so every
        host sees the full global device set (SURVEY.md §5 'Distributed
        communication backend')."""
        if num_processes <= 1:
            return cls.local()
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes,
            process_id=process_id,
        )
        return cls(rank=process_id, size=num_processes, initialized_jax_distributed=True)

    # -- control-plane collectives ------------------------------------

    def gather(self, obj: Any) -> Optional[List[Any]]:
        """Gather python objects to the chief (None on non-chief ranks)."""
        if self.size == 1:
            return [obj]
        vals = self.allgather(obj)
        return vals if self.is_chief else None

    def allgather(self, obj: Any) -> List[Any]:
        if self.size == 1:
            return [obj]
        from jax.experimental import multihost_utils

        return list(multihost_utils.process_allgather(_encode(obj)))  # type: ignore

    def broadcast(self, obj: Any) -> Any:
        if self.size == 1:
            return obj
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(_encode(obj))

    def barrier(self, name: str = "barrier") -> None:
        if self.size == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)

    def shutdown(self) -> None:
        if self.initialized_jax_distributed:
            import jax

            jax.distributed.shutdown()


def _encode(obj: Any) -> Any:
    # multihost_utils handles arrays/pytrees of arrays; plain python scalars
    # pass through np.asarray. Strings/dicts must be pre-encoded by callers
    # that need them; the framework only gathers numeric payloads here.
    import numpy as np

    if isinstance(obj, (int, float)):
        return np.asarray(obj)
    return obj
