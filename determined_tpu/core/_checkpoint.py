"""CheckpointContext — sharded GSPMD checkpointing + file checkpoints.

Reference: harness/determined/core/_checkpoint.py (upload :198 with shard=True,
store_path :475, download :406). TPU re-design:

  - Array state goes through **orbax/tensorstore**: every host writes its own
    shards of GSPMD arrays directly to storage (the TPU-native form of the
    reference's `shard=True` per-rank upload), and restore reshards to the
    current mesh — so a checkpoint taken on one mesh layout can resume on
    another (e.g. ASHA promoting a trial from a v5e-8 sub-slice to v5e-16).
  - Async by default: the save is snapshotted out of HBM and committed by a
    background thread, keeping the train loop on-MXU (BASELINE.md MFU target).
  - Arbitrary user files use the StorageManager upload/download path.
  - Metadata is reported to the master checkpoint registry when a session is
    present (reference post_ReportCheckpoint).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Any, Dict, Iterator, List, Optional

from determined_tpu.common.api import Session
from determined_tpu.storage.base import StorageManager

logger = logging.getLogger("determined_tpu.core")

_STATE_SUBDIR = "state"  # orbax pytree lives here inside the checkpoint dir
_METADATA_FILE = "metadata.json"


def _is_remote(path: str) -> bool:
    return "://" in path


def _dir_files(src: str, names: Optional[List[str]]) -> Dict[str, int]:
    """rel path -> size for the files upload() pushed from `src` (same walk
    as the storage upload implementations, storage/base.py)."""
    from determined_tpu.storage.base import iter_upload_files

    return {rel: os.path.getsize(p) for p, rel in iter_upload_files(src, names)}


class CheckpointContext:
    def __init__(
        self,
        session: Optional[Session],
        storage: StorageManager,
        trial_id: int = 0,
        allocation_id: Optional[str] = None,
        distributed=None,
        async_save: bool = True,
    ):
        self._session = session
        self._storage = storage
        self._trial_id = trial_id
        self._allocation_id = allocation_id
        self._dist = distributed
        self._async = async_save
        self._checkpointer = None
        self.local_reported: List[Dict[str, Any]] = []

    # -- orbax plumbing ------------------------------------------------

    def _ckptr(self):
        if self._checkpointer is None:
            import orbax.checkpoint as ocp

            if self._async:
                self._checkpointer = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler()
                )
            else:
                self._checkpointer = ocp.StandardCheckpointer()
        return self._checkpointer

    def _is_chief(self) -> bool:
        return self._dist is None or self._dist.is_chief

    # -- array-state checkpoints --------------------------------------

    def save_state(
        self,
        state: Any,
        steps_completed: int,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Save a pytree of (possibly sharded) jax arrays; returns storage id.

        All hosts must call this (collective); each writes its own shards.
        """
        # Deterministic id so all hosts agree without a broadcast.
        storage_id = f"trial{self._trial_id}-step{steps_completed}"
        path = self._array_path(storage_id)
        if (
            self._needs_staged_copy(path)
            and self._dist is not None
            and self._dist.size > 1
        ):
            # orbax's atomic finalize needs a filesystem all hosts can see;
            # host-local staging would silently drop non-primary shards.
            raise RuntimeError(
                "staged cloud backends (azure) do not support multi-host "
                "array checkpoints; use shared_fs or gcs for multi-host trials"
            )
        state_dir = path + "/" + _STATE_SUBDIR
        if not _is_remote(path):
            os.makedirs(path, exist_ok=True)
        self._ckptr().save(state_dir, state, force=True)
        md = dict(metadata or {})
        md.update(
            {
                "steps_completed": steps_completed,
                "trial_id": self._trial_id,
                "format": "orbax",
                "time": time.time(),
            }
        )
        if self._is_chief() and not _is_remote(path):
            with open(os.path.join(path, _METADATA_FILE), "w") as f:
                json.dump(md, f)
        if self._needs_staged_copy(path):
            # No tensorstore driver for this backend (azure): the orbax save
            # landed in local staging — push it to the bucket, then drop the
            # staging copy so periodic checkpointing doesn't fill /tmp. Every
            # host uploads its own shard files (reference shard=True
            # semantics).
            import shutil

            self.wait()
            try:
                self._storage.upload(path, storage_id)
            finally:
                shutil.rmtree(path, ignore_errors=True)
        self._report(storage_id, md)
        return storage_id

    def _needs_staged_copy(self, path: str) -> bool:
        return (
            not _is_remote(path)
            and getattr(self._storage, "requires_staging", False)
        )

    def _array_path(self, storage_id: str) -> str:
        """Where orbax reads/writes this checkpoint's arrays.

        Cloud managers expose url_for (gs://…) — tensorstore streams shards
        straight to the bucket, no staging copy; filesystem managers use the
        local path.
        """
        url_for = getattr(self._storage, "url_for", None)
        if url_for is not None:
            url = url_for(storage_id)
            if url:  # backends without a tensorstore scheme (azure) return None
                return url
        return os.path.abspath(self._storage.path_for(storage_id))

    def restore_state(self, storage_id: str, abstract_state: Any) -> Any:
        """Restore into the sharding/dtype layout of `abstract_state`.

        `abstract_state` is a pytree of jax.ShapeDtypeStruct (with .sharding
        set for sharded restore) or of concrete arrays serving as templates —
        e.g. the freshly-initialised TrainState. Works across mesh layouts:
        tensorstore reshards on read.
        """
        import jax

        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            abstract_state,
        )
        import orbax.checkpoint as ocp

        restorer = ocp.StandardCheckpointer()
        path = self._array_path(storage_id)
        if self._needs_staged_copy(path):
            # restore_path pulls a fresh copy from the bucket into staging
            # (never trusting this host's own stale/partial staging) and
            # cleans up afterwards.
            with self._storage.restore_path(storage_id) as local_path:
                state_dir = os.path.join(local_path, _STATE_SUBDIR)
                if not os.path.isdir(state_dir):
                    raise FileNotFoundError(
                        f"checkpoint {storage_id} has no array state in cloud storage"
                    )
                return restorer.restore(state_dir, abstract)
        if not _is_remote(path) and not os.path.isdir(path):
            raise FileNotFoundError(f"checkpoint {storage_id} not found at {path}")
        return restorer.restore(path + "/" + _STATE_SUBDIR, abstract)

    def load_metadata(self, storage_id: str) -> Dict[str, Any]:
        # Fetch only metadata.json — restore_path on a cloud backend would
        # download every shard of the checkpoint just to read one small file.
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            self._storage.download(
                storage_id, td, selector=lambda rel: rel == _METADATA_FILE
            )
            md_file = os.path.join(td, _METADATA_FILE)
            if os.path.exists(md_file):
                with open(md_file) as f:
                    return json.load(f)
        # No metadata file: distinguish "checkpoint without metadata" from a
        # bad storage id (download() is a no-op for missing ids).
        if not self._storage.list_files(storage_id):
            raise FileNotFoundError(f"checkpoint {storage_id} not found")
        return {}

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        c = self._checkpointer
        if c is not None and hasattr(c, "wait_until_finished"):
            c.wait_until_finished()

    def close(self) -> None:
        self.wait()
        if self._checkpointer is not None:
            self._checkpointer.close()
            self._checkpointer = None

    # -- file checkpoints (reference upload/download/store_path) -------

    @contextlib.contextmanager
    def store_path(self, metadata: Optional[Dict[str, Any]] = None) -> Iterator[tuple]:
        """Chief-only convenience: yield (path, storage_id); report on exit."""
        md = dict(metadata or {})
        with self._storage.store_path() as (storage_id, path):
            yield path, storage_id
            md.setdefault("time", time.time())
            if self._is_chief():
                with open(os.path.join(path, _METADATA_FILE), "w") as f:
                    json.dump(md, f)
        # Report after the storage context exits — cloud backends upload on
        # exit, so list_files() inside _report sees the final bucket contents.
        self._report(storage_id, md)

    def upload(
        self,
        ckpt_dir: str,
        metadata: Optional[Dict[str, Any]] = None,
        shard: bool = False,
        selector=None,
    ) -> str:
        """Upload a directory as a checkpoint.

        shard=True: every rank uploads its own files into the same storage id
        (rank-unique filenames are the caller's contract, as in the reference
        core/_checkpoint.py:282); each rank's uploaded-file metadata is
        gathered to the chief over the object control plane and reported
        merged, so the registry knows the full resource list even on
        non-shared storage.
        """
        sharded = shard and self._dist is not None and self._dist.size > 1
        if sharded:
            # All hosts must agree on the id: chief generates, broadcast as a
            # python string over the object control plane.
            storage_id = self._dist.broadcast(self._storage.new_storage_id())
        else:
            storage_id = self._storage.new_storage_id()
        names = None
        if selector is not None:
            names = [n for n in os.listdir(ckpt_dir) if selector(n)]
        local_files: Dict[str, int] = {}
        if shard or self._is_chief():
            self._storage.upload(ckpt_dir, storage_id, names)
            local_files = _dir_files(ckpt_dir, names)
        md = dict(metadata or {})
        md.setdefault("time", time.time())
        resources: Optional[Dict[str, int]] = None
        if sharded:
            # gather doubles as the all-uploads-finished barrier before the
            # chief registers the checkpoint (reference metadata merge,
            # core/_checkpoint.py:282).
            gathered = self._dist.gather(local_files)
            if gathered is not None:
                resources = {}
                for files in gathered:
                    resources.update(files)
        self._report(storage_id, md, resources=resources)
        return storage_id

    def download(self, storage_id: str, ckpt_dir: str, selector=None) -> None:
        self._storage.download(storage_id, ckpt_dir, selector)

    @contextlib.contextmanager
    def restore_path(self, storage_id: str) -> Iterator[str]:
        with self._storage.restore_path(storage_id) as path:
            yield path

    def delete(self, storage_id: str) -> None:
        if self._is_chief():
            self._storage.delete(storage_id)

    # -- master reporting ---------------------------------------------

    def _report(
        self,
        storage_id: str,
        metadata: Dict[str, Any],
        resources: Optional[Dict[str, int]] = None,
    ) -> None:
        if not self._is_chief():
            return
        record = {
            "uuid": storage_id,
            "trial_id": self._trial_id,
            "allocation_id": self._allocation_id,
            "metadata": metadata,
            "steps_completed": metadata.get("steps_completed", 0),
            "resources": resources or {},
        }
        if self._session is None:
            self.local_reported.append(record)
            return
        if resources is None:
            try:
                record["resources"] = self._storage.list_files(storage_id)
            except Exception:
                pass
        # idempotent: a retried report must not double-register the
        # checkpoint or re-bump the trial's resume pointer.
        self._session.post("/api/v1/checkpoints", body=record, idempotent=True)
