"""CheckpointContext — sharded GSPMD checkpointing + file checkpoints.

Reference: harness/determined/core/_checkpoint.py (upload :198 with shard=True,
store_path :475, download :406). TPU re-design:

  - Array state goes through **orbax/tensorstore**: every host writes its own
    shards of GSPMD arrays directly to storage (the TPU-native form of the
    reference's `shard=True` per-rank upload), and restore reshards to the
    current mesh — so a checkpoint taken on one mesh layout can resume on
    another (e.g. ASHA promoting a trial from a v5e-8 sub-slice to v5e-16).
  - Async by default: the save is snapshotted out of HBM and committed by a
    background thread, keeping the train loop on-MXU (BASELINE.md MFU target).
  - Arbitrary user files use the StorageManager upload/download path.
  - Metadata is reported to the master checkpoint registry when a session is
    present (reference post_ReportCheckpoint).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import time
from typing import Any, Dict, Iterator, List, Optional

from determined_tpu.common.api import Session
from determined_tpu.common import trace as trace_mod
from determined_tpu.core import _integrity
from determined_tpu.core._integrity import CorruptCheckpoint  # noqa: F401  (re-export)
from determined_tpu.storage.base import StorageManager

logger = logging.getLogger("determined_tpu.core")

_STATE_SUBDIR = "state"  # orbax pytree lives here inside the checkpoint dir
_METADATA_FILE = "metadata.json"

# save_state ids are deterministic so all hosts agree without a broadcast —
# and so lineage() can recover the step ordering from storage alone.
_STATE_ID_RE = re.compile(r"^trial(\d+)-step(\d+)$")


def state_id_step(storage_id: str) -> Optional[int]:
    """Step number encoded in a save_state id (None for other ids)."""
    m = _STATE_ID_RE.match(storage_id)
    return int(m.group(2)) if m else None


def _is_remote(path: str) -> bool:
    return "://" in path


def _dir_files(src: str, names: Optional[List[str]]) -> Dict[str, int]:
    """rel path -> size for the files upload() pushed from `src` (same walk
    as the storage upload implementations, storage/base.py)."""
    from determined_tpu.storage.base import iter_upload_files

    return {rel: os.path.getsize(p) for p, rel in iter_upload_files(src, names)}


class CheckpointContext:
    def __init__(
        self,
        session: Optional[Session],
        storage: StorageManager,
        trial_id: int = 0,
        allocation_id: Optional[str] = None,
        distributed=None,
        async_save: bool = True,
    ):
        self._session = session
        self._storage = storage
        self._trial_id = trial_id
        self._allocation_id = allocation_id
        self._dist = distributed
        self._async = async_save
        self._checkpointer = None
        # (storage_id, path, metadata) of an async save whose phase-2 commit
        # (manifest + COMMIT marker + COMPLETED report) is still pending.
        self._pending_commit: Optional[tuple] = None
        # Observed durable-save cost of the most recent checkpoint: the
        # synchronous portion of save_state plus the BLOCKING portion of
        # the wait that committed it. The Trainer budgets spot-preemption
        # emergency checkpoints against this (docs/checkpointing.md).
        # Under async overlap the blocking part shrinks (the write
        # finished during training), so this underestimates a cold
        # synchronous save — the safety factor in PreemptionConfig covers
        # the gap, and the two-phase commit keeps a blown budget from ever
        # becoming a restorable torso.
        self.last_save_ms: Optional[float] = None
        self._pending_sync_ms = 0.0
        self.local_reported: List[Dict[str, Any]] = []
        # Lifecycle tracing (docs/observability.md): set by core.init —
        # phase-1 saves and phase-2 commits land on the trial's trace.
        self.tracer = None

    def _span(self, name: str, start_us: int, **attrs) -> None:
        if self.tracer is not None:
            self.tracer.emit(name, start_us, trace_mod.now_us(), attrs)

    # -- orbax plumbing ------------------------------------------------

    def _ckptr(self):
        if self._checkpointer is None:
            import orbax.checkpoint as ocp

            if self._async:
                self._checkpointer = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler()
                )
            else:
                self._checkpointer = ocp.StandardCheckpointer()
        return self._checkpointer

    def _is_chief(self) -> bool:
        return self._dist is None or self._dist.is_chief

    # -- array-state checkpoints --------------------------------------

    def save_state(
        self,
        state: Any,
        steps_completed: int,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Save a pytree of (possibly sharded) jax arrays; returns storage id.

        All hosts must call this (collective); each writes its own shards.

        Two-phase commit (docs/checkpointing.md): the orbax save is phase 1
        and may complete asynchronously; the checkpoint is reported PARTIAL
        immediately and only flips to COMPLETED — manifest + COMMIT marker
        written, registry updated, resume pointer advanced — once the save
        is durable (at the next `wait()` / `save_state` / `close()`).
        """
        # A previous async save still pending phase 2 commits first — orbax
        # would block on it inside save() anyway, so this costs nothing
        # extra and keeps at most one checkpoint in the PARTIAL window.
        self.wait()
        # Deterministic id so all hosts agree without a broadcast.
        storage_id = f"trial{self._trial_id}-step{steps_completed}"
        path = self._array_path(storage_id)
        if (
            self._needs_staged_copy(path)
            and self._dist is not None
            and self._dist.size > 1
        ):
            # orbax's atomic finalize needs a filesystem all hosts can see;
            # host-local staging would silently drop non-primary shards.
            raise RuntimeError(
                "staged cloud backends (azure) do not support multi-host "
                "array checkpoints; use shared_fs or gcs for multi-host trials"
            )
        state_dir = path + "/" + _STATE_SUBDIR
        if not _is_remote(path):
            os.makedirs(path, exist_ok=True)
        t0 = time.monotonic()
        t0_us = trace_mod.now_us()
        self._ckptr().save(state_dir, state, force=True)
        self._pending_sync_ms = (time.monotonic() - t0) * 1000.0
        # Phase 1 on the lifecycle trace: the synchronous save portion the
        # train loop actually paid for (async overlap hides the rest).
        self._span("harness.checkpoint.save", t0_us, storage_id=storage_id,
                   steps_completed=steps_completed)
        md = dict(metadata or {})
        md.update(
            {
                "steps_completed": steps_completed,
                "trial_id": self._trial_id,
                "format": "orbax",
                "time": time.time(),
            }
        )
        if self._is_chief():
            if not _is_remote(path):
                with open(os.path.join(path, _METADATA_FILE), "w") as f:
                    json.dump(md, f)
            else:
                # Remote (tensorstore-native) paths used to get NO metadata
                # file at all — load_metadata returned {} and resume lost
                # steps_completed. Stage it locally and upload.
                self._upload_small_files(storage_id,
                                         {_METADATA_FILE: json.dumps(md)})
        self._report(storage_id, md, state="PARTIAL")
        if self._needs_staged_copy(path):
            # No tensorstore driver for this backend (azure): the orbax save
            # landed in local staging — commit it there, push everything
            # (shards + manifest + COMMIT) to the bucket, then drop the
            # staging copy so periodic checkpointing doesn't fill /tmp.
            # Every host uploads its own shard files (reference shard=True
            # semantics).
            import shutil

            t0 = time.monotonic()
            t0_us = trace_mod.now_us()
            self.wait()
            try:
                if self._is_chief():
                    _integrity.commit(path, storage_id)
                self._storage.upload(path, storage_id)
            finally:
                shutil.rmtree(path, ignore_errors=True)
            self._report(storage_id, md, state="COMPLETED")
            self.last_save_ms = (
                self._pending_sync_ms + (time.monotonic() - t0) * 1000.0)
            self._span("harness.checkpoint.commit", t0_us,
                       storage_id=storage_id, staged=True)
            return storage_id
        self._pending_commit = (storage_id, path, md)
        if not self._async:
            self.wait()
        return storage_id

    def _upload_small_files(self, storage_id: str,
                            files: Dict[str, str]) -> None:
        """Stage name->content strings into a tempdir and upload them into
        the checkpoint (used for metadata/manifest/COMMIT on remote paths,
        where there is no local directory to write into)."""
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            for name, content in files.items():
                with open(os.path.join(td, name), "w") as f:
                    f.write(content)
            self._storage.upload(td, storage_id, list(files))

    def _commit_pending(self) -> None:
        """Phase 2 for the pending async save: manifest + COMMIT + the
        COMPLETED report. Caller must have made the save durable (wait)."""
        if self._pending_commit is None:
            return
        storage_id, path, md = self._pending_commit
        self._pending_commit = None
        if self._is_chief():
            if not _is_remote(path):
                _integrity.commit(path, storage_id)
            else:
                # Object stores expose no rename, but object creation is
                # atomic; checksums would require re-downloading every
                # shard, so the remote manifest records presence + size.
                listing = {
                    rel: size
                    for rel, size in self._storage.list_files(storage_id).items()
                    if rel not in (_integrity.MANIFEST_FILE,
                                   _integrity.COMMIT_FILE)
                }
                manifest = {"version": 1,
                            "files": {rel: {"size": size}
                                      for rel, size in sorted(listing.items())}}
                from determined_tpu.common import faultpoint

                files = {_integrity.MANIFEST_FILE:
                         json.dumps(manifest, sort_keys=True)}
                if faultpoint.fire(_integrity.FAULT_COMMIT_DROP) is \
                        faultpoint.Action.NONE:
                    files[_integrity.COMMIT_FILE] = json.dumps(
                        {"storage_id": storage_id,
                         "n_files": len(listing)})
                self._upload_small_files(storage_id, files)
        self._report(storage_id, md, state="COMPLETED")

    def _needs_staged_copy(self, path: str) -> bool:
        return (
            not _is_remote(path)
            and getattr(self._storage, "requires_staging", False)
        )

    def _array_path(self, storage_id: str) -> str:
        """Where orbax reads/writes this checkpoint's arrays.

        Cloud managers expose url_for (gs://…) — tensorstore streams shards
        straight to the bucket, no staging copy; filesystem managers use the
        local path.
        """
        url_for = getattr(self._storage, "url_for", None)
        if url_for is not None:
            url = url_for(storage_id)
            if url:  # backends without a tensorstore scheme (azure) return None
                return url
        return os.path.abspath(self._storage.path_for(storage_id))

    def restore_state(self, storage_id: str, abstract_state: Any) -> Any:
        """Restore into the sharding/dtype layout of `abstract_state`.

        `abstract_state` is a pytree of jax.ShapeDtypeStruct (with .sharding
        set for sharded restore) or of concrete arrays serving as templates —
        e.g. the freshly-initialised TrainState. Works across mesh layouts:
        tensorstore reshards on read.
        """
        import jax

        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape")
            else x,
            abstract_state,
        )
        import orbax.checkpoint as ocp

        restorer = ocp.StandardCheckpointer()
        path = self._array_path(storage_id)
        if self._needs_staged_copy(path):
            # restore_path pulls a fresh copy from the bucket into staging
            # (never trusting this host's own stale/partial staging) and
            # cleans up afterwards. Verify the downloaded copy — the
            # manifest + COMMIT came down with it.
            with self._storage.restore_path(storage_id) as local_path:
                state_dir = os.path.join(local_path, _STATE_SUBDIR)
                if not os.path.isdir(state_dir):
                    raise FileNotFoundError(
                        f"checkpoint {storage_id} has no array state in cloud storage"
                    )
                _integrity.verify(local_path, storage_id)
                return restorer.restore(state_dir, abstract)
        if _is_remote(path):
            self._verify_remote(storage_id)
            return restorer.restore(path + "/" + _STATE_SUBDIR, abstract)
        if not os.path.isdir(path):
            raise FileNotFoundError(f"checkpoint {storage_id} not found at {path}")
        _integrity.verify(path, storage_id)
        return restorer.restore(path + "/" + _STATE_SUBDIR, abstract)

    def _verify_remote(self, storage_id: str) -> None:
        """Integrity check for tensorstore-native (gs://) checkpoints:
        download only the two protocol files and verify the bucket listing
        against the manifest (presence + size; checksumming would download
        every shard)."""
        import tempfile

        listing = self._storage.list_files(storage_id)
        manifest = None
        with tempfile.TemporaryDirectory() as td:
            self._storage.download(
                storage_id, td,
                selector=lambda rel: rel == _integrity.MANIFEST_FILE)
            mf = os.path.join(td, _integrity.MANIFEST_FILE)
            if os.path.exists(mf):
                try:
                    with open(mf) as f:
                        manifest = json.load(f)
                except (OSError, ValueError):
                    manifest = None
        _integrity.verify_listing(listing, manifest, storage_id)

    def verify(self, storage_id: str) -> bool:
        """Standalone integrity check (no restore). True = manifest fully
        verified; False = legacy checkpoint (predates the protocol);
        raises CorruptCheckpoint / FileNotFoundError otherwise."""
        path = self._array_path(storage_id)
        if self._needs_staged_copy(path) or _is_remote(path):
            self._verify_remote(storage_id)
            return True
        if not os.path.isdir(path):
            raise FileNotFoundError(f"checkpoint {storage_id} not found at {path}")
        return _integrity.verify(path, storage_id)

    def lineage(self) -> List[str]:
        """This trial's COMPLETED checkpoints, newest first — the fallback
        chain `Trainer._restore` walks when the latest checkpoint is
        corrupt or missing (Gemini-style known-good lineage).

        Managed mode asks the master registry (which only marks a
        checkpoint COMPLETED after the phase-2 commit report); local /
        masterless mode reconstructs the lineage from in-process reports
        plus the deterministic `trial{N}-step{M}` ids found in storage
        (committed ones only), so a restarted local process still sees it.
        """
        if self._session is not None:
            try:
                resp = self._session.get(
                    f"/api/v1/trials/{self._trial_id}/checkpoints",
                    params={"state": "COMPLETED"})
                return [c["uuid"] for c in resp.get("checkpoints", [])]
            except Exception:
                logger.warning("lineage query failed; falling back to "
                               "storage scan", exc_info=True)
        steps: Dict[str, int] = {}
        for rec in self.local_reported:
            if rec.get("state", "COMPLETED") != "COMPLETED":
                continue
            m = _STATE_ID_RE.match(rec["uuid"])
            if m and int(m.group(1)) == self._trial_id:
                steps[rec["uuid"]] = int(m.group(2))
        base = getattr(self._storage, "base_path", None)
        if base and os.path.isdir(base):
            for name in os.listdir(base):
                m = _STATE_ID_RE.match(name)
                if not m or int(m.group(1)) != self._trial_id:
                    continue
                if name in steps:
                    continue
                # Only committed checkpoints join the lineage; an
                # uncommitted dir is exactly what fallback must skip.
                if os.path.exists(os.path.join(
                        base, name, _integrity.COMMIT_FILE)):
                    steps[name] = int(m.group(2))
        return sorted(steps, key=steps.__getitem__, reverse=True)

    def load_metadata(self, storage_id: str) -> Dict[str, Any]:
        # Fetch only metadata.json — restore_path on a cloud backend would
        # download every shard of the checkpoint just to read one small file.
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            self._storage.download(
                storage_id, td, selector=lambda rel: rel == _METADATA_FILE
            )
            md_file = os.path.join(td, _METADATA_FILE)
            if os.path.exists(md_file):
                with open(md_file) as f:
                    return json.load(f)
        # No metadata file: distinguish "checkpoint without metadata" from a
        # bad storage id (download() is a no-op for missing ids).
        if not self._storage.list_files(storage_id):
            raise FileNotFoundError(f"checkpoint {storage_id} not found")
        return {}

    def wait(self) -> None:
        """Block until pending async saves are durable AND committed
        (manifest + COMMIT marker written, COMPLETED reported)."""
        had_pending = self._pending_commit is not None
        pending_id = self._pending_commit[0] if had_pending else None
        t0 = time.monotonic()
        t0_us = trace_mod.now_us()
        c = self._checkpointer
        if c is not None and hasattr(c, "wait_until_finished"):
            c.wait_until_finished()
        self._commit_pending()
        if had_pending:
            self.last_save_ms = (
                self._pending_sync_ms + (time.monotonic() - t0) * 1000.0)
            # Phase 2 on the lifecycle trace: durability wait + manifest +
            # COMMIT + the COMPLETED report.
            self._span("harness.checkpoint.commit", t0_us,
                       storage_id=pending_id)

    def close(self) -> None:
        self.wait()
        if self._checkpointer is not None:
            self._checkpointer.close()
            self._checkpointer = None

    # -- file checkpoints (reference upload/download/store_path) -------

    @contextlib.contextmanager
    def store_path(self, metadata: Optional[Dict[str, Any]] = None) -> Iterator[tuple]:
        """Chief-only convenience: yield (path, storage_id); report on exit."""
        md = dict(metadata or {})
        with self._storage.store_path() as (storage_id, path):
            yield path, storage_id
            md.setdefault("time", time.time())
            if self._is_chief():
                with open(os.path.join(path, _METADATA_FILE), "w") as f:
                    json.dump(md, f)
        # Report after the storage context exits — cloud backends upload on
        # exit, so list_files() inside _report sees the final bucket contents.
        self._report(storage_id, md)

    def upload(
        self,
        ckpt_dir: str,
        metadata: Optional[Dict[str, Any]] = None,
        shard: bool = False,
        selector=None,
    ) -> str:
        """Upload a directory as a checkpoint.

        shard=True: every rank uploads its own files into the same storage id
        (rank-unique filenames are the caller's contract, as in the reference
        core/_checkpoint.py:282); each rank's uploaded-file metadata is
        gathered to the chief over the object control plane and reported
        merged, so the registry knows the full resource list even on
        non-shared storage.
        """
        sharded = shard and self._dist is not None and self._dist.size > 1
        if sharded:
            # All hosts must agree on the id: chief generates, broadcast as a
            # python string over the object control plane.
            storage_id = self._dist.broadcast(self._storage.new_storage_id())
        else:
            storage_id = self._storage.new_storage_id()
        names = None
        if selector is not None:
            names = [n for n in os.listdir(ckpt_dir) if selector(n)]
        local_files: Dict[str, int] = {}
        if shard or self._is_chief():
            self._storage.upload(ckpt_dir, storage_id, names)
            local_files = _dir_files(ckpt_dir, names)
        md = dict(metadata or {})
        md.setdefault("time", time.time())
        resources: Optional[Dict[str, int]] = None
        if sharded:
            # gather doubles as the all-uploads-finished barrier before the
            # chief registers the checkpoint (reference metadata merge,
            # core/_checkpoint.py:282).
            gathered = self._dist.gather(local_files)
            if gathered is not None:
                resources = {}
                for files in gathered:
                    resources.update(files)
        self._report(storage_id, md, resources=resources)
        return storage_id

    def download(self, storage_id: str, ckpt_dir: str, selector=None) -> None:
        self._storage.download(storage_id, ckpt_dir, selector)

    @contextlib.contextmanager
    def restore_path(self, storage_id: str) -> Iterator[str]:
        with self._storage.restore_path(storage_id) as path:
            yield path

    def delete(self, storage_id: str) -> None:
        if self._is_chief():
            self._storage.delete(storage_id)

    # -- master reporting ---------------------------------------------

    def _report(
        self,
        storage_id: str,
        metadata: Dict[str, Any],
        resources: Optional[Dict[str, int]] = None,
        state: str = "COMPLETED",
    ) -> None:
        if not self._is_chief():
            return
        record = {
            "uuid": storage_id,
            "trial_id": self._trial_id,
            "allocation_id": self._allocation_id,
            "metadata": metadata,
            "steps_completed": metadata.get("steps_completed", 0),
            "resources": resources or {},
            "state": state,
        }
        if self._session is None:
            # The phase-2 COMPLETED report updates the PARTIAL record in
            # place, mirroring the master's INSERT OR REPLACE — one record
            # per checkpoint either way.
            for i, rec in enumerate(self.local_reported):
                if rec["uuid"] == storage_id:
                    self.local_reported[i] = record
                    return
            self.local_reported.append(record)
            return
        if resources is None:
            try:
                record["resources"] = self._storage.list_files(storage_id)
            except Exception:
                pass
        # idempotent: a retried report must not double-register the
        # checkpoint or re-bump the trial's resume pointer.
        self._session.post("/api/v1/checkpoints", body=record, idempotent=True)
