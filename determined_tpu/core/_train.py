"""TrainContext — metric reporting (reference harness/determined/core/_train.py:20).

Master mode POSTs to `ReportTrialMetrics` (reference api_trials.go:1381);
local mode accumulates in-memory and logs, so the same training code runs
with or without a cluster.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Dict, List, Optional

from determined_tpu.common.api import Session

logger = logging.getLogger("determined_tpu.core")


def _clean_metrics(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe scalars: device arrays → python floats; NaN/Inf → strings."""
    out: Dict[str, Any] = {}
    for k, v in metrics.items():
        if hasattr(v, "item"):
            try:
                v = v.item()
            except Exception:
                continue
        if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
            v = str(v)
        out[k] = v
    return out


class TrainContext:
    def __init__(
        self,
        session: Optional[Session],
        trial_id: int = 0,
        run_id: int = 0,
        distributed=None,
        tensorboard_manager=None,
    ):
        self._session = session
        self._trial_id = trial_id
        self._run_id = run_id
        self._dist = distributed
        self._tb = tensorboard_manager
        # local-mode metric store (inspectable by tests / local callers)
        self.local_training_metrics: List[Dict[str, Any]] = []
        self.local_validation_metrics: List[Dict[str, Any]] = []

    def _report(self, group: str, steps_completed: int, metrics: Dict[str, Any]) -> None:
        if self._dist is not None and not self._dist.is_chief:
            return
        metrics = _clean_metrics(metrics)
        if self._tb is not None:
            self._tb.on_metrics(group, steps_completed, metrics)
        record = {
            "trial_id": self._trial_id,
            "trial_run_id": self._run_id,
            "group": group,
            "steps_completed": steps_completed,
            "metrics": metrics,
            "report_time": time.time(),
        }
        if self._session is None:
            store = (
                self.local_training_metrics
                if group == "training"
                else self.local_validation_metrics
            )
            store.append(record)
            logger.info("[%s] step=%d %s", group, steps_completed, metrics)
        else:
            # idempotent: a retry after a lost response must not
            # double-count this report (master-side replay cache).
            self._session.post(
                f"/api/v1/trials/{self._trial_id}/metrics",
                body=record,
                idempotent=True,
            )

    def report_training_metrics(self, steps_completed: int, metrics: Dict[str, Any]) -> None:
        self._report("training", steps_completed, metrics)

    def report_validation_metrics(self, steps_completed: int, metrics: Dict[str, Any]) -> None:
        self._report("validation", steps_completed, metrics)

    def report_metrics(self, group: str, steps_completed: int, metrics: Dict[str, Any]) -> None:
        """Arbitrary metric groups (reference: report_metrics / custom groups)."""
        self._report(group, steps_completed, metrics)

    def report_progress(self, progress: float) -> None:
        if self._session is None or (self._dist and not self._dist.is_chief):
            return
        self._session.post(
            f"/api/v1/trials/{self._trial_id}/progress",
            body={"progress": float(progress)},
        )

    def set_status(self, status: str) -> None:
        if self._session is None or (self._dist and not self._dist.is_chief):
            return
        try:
            self._session.post(
                f"/api/v1/trials/{self._trial_id}/runner/metadata",
                body={"state": status},
            )
        except Exception:
            logger.debug("set_status(%s) failed", status, exc_info=True)
