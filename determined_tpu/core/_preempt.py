"""PreemptContext — cooperative preemption (reference
harness/determined/core/_preempt.py:148; watcher thread :15 long-polls
`GET /api/v1/allocations/{id}/signals/preemption`, api_trials.go:1179).

The scheduler preempts a trial by raising its preemption flag; the training
loop polls `should_preempt()` at step boundaries, checkpoints, and exits.
Multi-host: only the chief polls the master; the decision is broadcast so all
hosts leave their collectives in lockstep.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from determined_tpu.common.api import Session

logger = logging.getLogger("determined_tpu.core")


class _PreemptionWatcher(threading.Thread):
    """Daemon thread long-polling the master for the preemption signal."""

    def __init__(self, session: Session, allocation_id: str, poll_timeout: int = 60):
        super().__init__(daemon=True, name="preemption-watcher")
        self._session = session
        self._allocation_id = allocation_id
        self._poll_timeout = poll_timeout
        self._preempted = threading.Event()
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                resp = self._session.get(
                    f"/api/v1/allocations/{self._allocation_id}/signals/preemption",
                    params={"timeout_seconds": self._poll_timeout},
                    timeout=self._poll_timeout + 30,
                )
                if resp and resp.get("preempt"):
                    self._preempted.set()
                    return
            except Exception:
                if not self._stop.is_set():
                    logger.debug("preemption poll failed; retrying", exc_info=True)
                    self._stop.wait(5.0)

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    def close(self) -> None:
        self._stop.set()


class PreemptContext:
    def __init__(
        self,
        session: Optional[Session],
        allocation_id: Optional[str] = None,
        distributed=None,
    ):
        self._session = session
        self._allocation_id = allocation_id
        self._dist = distributed
        self._watcher: Optional[_PreemptionWatcher] = None
        self._forced = False  # local-mode / test hook
        if session is not None and allocation_id and (
            distributed is None or distributed.is_chief
        ):
            self._watcher = _PreemptionWatcher(session, allocation_id)
            self._watcher.start()

    def should_preempt(self, auto_ack: bool = True) -> bool:
        flag = self._forced or (self._watcher is not None and self._watcher.preempted)
        if self._dist is not None and self._dist.size > 1:
            flag = bool(self._dist.broadcast(int(flag)))
        if flag and auto_ack:
            self.acknowledge_preemption_signal()
        return flag

    def acknowledge_preemption_signal(self) -> None:
        """Tell the master we saw the signal and will checkpoint+exit
        (reference ack_preemption, _preempt.py:257)."""
        if self._session is not None and self._allocation_id and (
            self._dist is None or self._dist.is_chief
        ):
            try:
                self._session.post(
                    f"/api/v1/allocations/{self._allocation_id}/signals/ack_preemption"
                )
            except Exception:
                logger.debug("ack_preemption failed", exc_info=True)

    def force(self) -> None:
        """Local/test hook: behave as if preempted."""
        self._forced = True

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.close()
