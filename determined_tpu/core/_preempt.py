"""PreemptContext — cooperative + deadline preemption (reference
harness/determined/core/_preempt.py:148; watcher thread :15 long-polls
`GET /api/v1/allocations/{id}/signals/preemption`, api_trials.go:1179).

Two flavors of preemption ride the same signal:

  - **Cooperative** (scheduler-initiated: pause, higher-priority job): an
    unbounded flag; the training loop polls `should_preempt()` at step
    boundaries, checkpoints, and exits whenever it gets there.
  - **Deadline** (infrastructure-initiated: GCE spot preemption, TPU
    maintenance, SIGTERM to the agent): the signal carries
    `deadline_seconds` — the node disappears when it lapses.
    `preemption_deadline()` exposes the REMAINING grace so the Trainer can
    budget an out-of-band emergency checkpoint (docs/checkpointing.md).

Multi-host: only the chief polls the master; both the decision and the
deadline are broadcast so all hosts leave their collectives in lockstep.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from determined_tpu.common.api import Session

logger = logging.getLogger("determined_tpu.core")


class _PreemptionWatcher(threading.Thread):
    """Daemon thread long-polling the master for the preemption signal."""

    def __init__(
        self,
        session: Session,
        allocation_id: str,
        poll_timeout: int = 60,
        backoff_base: float = 0.5,
        backoff_cap: float = 10.0,
    ):
        super().__init__(daemon=True, name="preemption-watcher")
        self._session = session
        self._allocation_id = allocation_id
        self._poll_timeout = poll_timeout
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        self._preempted = threading.Event()
        self._stop_evt = threading.Event()
        self._deadline: Optional[float] = None  # time.monotonic() absolute
        self._reason: Optional[str] = None
        # Elastic resize offer (docs/elasticity.md): target slot count the
        # scheduler wants this trial resharded to. Rides the same signal.
        self._resize_target: Optional[int] = None

    def run(self) -> None:
        backoff = 0.0
        while not self._stop_evt.is_set():
            try:
                resp = self._session.get(
                    f"/api/v1/allocations/{self._allocation_id}/signals/preemption",
                    params={"timeout_seconds": self._poll_timeout},
                    timeout=self._poll_timeout + 30,
                )
            except Exception:
                if self._stop_evt.is_set():
                    return
                logger.debug("preemption poll failed; retrying", exc_info=True)
                backoff = min(self._backoff_cap,
                              max(self._backoff_base, backoff * 2))
                self._stop_evt.wait(backoff)
                continue
            if isinstance(resp, dict):
                backoff = 0.0
                if resp.get("preempt"):
                    deadline = resp.get("deadline_seconds")
                    if deadline is not None:
                        try:
                            self._deadline = (
                                time.monotonic() + max(0.0, float(deadline)))
                        except (TypeError, ValueError):
                            logger.warning(
                                "unparseable preemption deadline %r; "
                                "treating as unbounded", deadline)
                    self._reason = resp.get("reason") or None
                    if resp.get("resize"):
                        target = resp.get("target_slots")
                        try:
                            target = int(target)
                        except (TypeError, ValueError):
                            target = 0
                        if target > 0:
                            self._resize_target = target
                        else:
                            logger.warning(
                                "resize signal with unusable target_slots "
                                "%r; treating as a plain preemption",
                                resp.get("target_slots"))
                    self._preempted.set()
                    return
                # A well-formed long-poll return without a signal (the
                # master's wait timed out): re-poll immediately — that IS
                # the long-poll protocol.
                continue
            # Successful but falsy/garbage response (master restarting
            # behind a proxy, empty body): hot-looping here used to spin
            # the poll at full rate — back off, capped.
            backoff = min(self._backoff_cap, max(self._backoff_base, backoff * 2))
            self._stop_evt.wait(backoff)

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    @property
    def deadline(self) -> Optional[float]:
        """Absolute time.monotonic() deadline, set before `preempted`."""
        return self._deadline

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    @property
    def resize_target(self) -> Optional[int]:
        """Requested slot count of a resize offer, set before `preempted`."""
        return self._resize_target

    def close(self, timeout: float = 5.0) -> None:
        """Stop and join (bounded). A watcher blocked in a live long-poll
        returns at the poll timeout; the bound keeps close() from being
        held hostage by it, at the cost of the daemon thread lingering
        until then."""
        self._stop_evt.set()
        if self.ident is not None:
            self.join(timeout=timeout)


class PreemptContext:
    def __init__(
        self,
        session: Optional[Session],
        allocation_id: Optional[str] = None,
        distributed=None,
    ):
        self._session = session
        self._allocation_id = allocation_id
        self._dist = distributed
        self._watcher: Optional[_PreemptionWatcher] = None
        self._forced = False  # local-mode / test hook
        self._forced_deadline: Optional[float] = None  # monotonic absolute
        self._forced_resize: Optional[int] = None
        if session is not None and allocation_id and (
            distributed is None or distributed.is_chief
        ):
            self._watcher = _PreemptionWatcher(session, allocation_id)
            self._watcher.start()

    def should_preempt(self, auto_ack: bool = True) -> bool:
        flag = self._forced or (self._watcher is not None and self._watcher.preempted)
        if self._dist is not None and self._dist.size > 1:
            flag = bool(self._dist.broadcast(int(flag)))
        if flag and auto_ack:
            self.acknowledge_preemption_signal()
        return flag

    def preemption_deadline(self) -> Optional[float]:
        """Seconds remaining in the termination grace window, or None for
        an ordinary (unbounded) preemption / no preemption at all.

        Counts DOWN between calls. Broadcast from the chief so every host
        takes the same emergency-checkpoint decision (the save is a
        collective)."""
        remaining: Optional[float] = None
        if self._forced_deadline is not None:
            remaining = max(0.0, self._forced_deadline - time.monotonic())
        elif self._watcher is not None and self._watcher.deadline is not None:
            remaining = max(0.0, self._watcher.deadline - time.monotonic())
        if self._dist is not None and self._dist.size > 1:
            value = -1.0 if remaining is None else float(remaining)
            value = float(self._dist.broadcast(value))
            remaining = None if value < 0 else value
        return remaining

    def resize_target(self) -> Optional[int]:
        """Elastic resize offer (docs/elasticity.md): the slot count the
        scheduler wants this trial resharded to, or None when the current
        preemption (if any) is an ordinary one. Broadcast from the chief so
        every host takes the same resize-vs-exit decision."""
        target: Optional[int] = None
        if self._forced_resize is not None:
            target = self._forced_resize
        elif self._watcher is not None and \
                self._watcher.resize_target is not None:
            target = self._watcher.resize_target
        if self._dist is not None and self._dist.size > 1:
            value = -1 if target is None else int(target)
            value = int(self._dist.broadcast(value))
            target = None if value <= 0 else value
        return target

    def preemption_reason(self) -> Optional[str]:
        """Why the preemption happened (e.g. "spot_preemption",
        "host_maintenance"); None when unknown / not preempted."""
        if self._watcher is not None and self._watcher.reason:
            return self._watcher.reason
        if self._forced:
            return "forced"
        return None

    def acknowledge_preemption_signal(self) -> None:
        """Tell the master we saw the signal and will checkpoint+exit
        (reference ack_preemption, _preempt.py:257)."""
        if self._session is not None and self._allocation_id and (
            self._dist is None or self._dist.is_chief
        ):
            try:
                self._session.post(
                    f"/api/v1/allocations/{self._allocation_id}/signals/ack_preemption"
                )
            except Exception:
                logger.debug("ack_preemption failed", exc_info=True)

    def force(self, deadline: Optional[float] = None) -> None:
        """Local/test hook: behave as if preempted — with a termination
        deadline `deadline` seconds out when given."""
        self._forced = True
        if deadline is not None:
            self._forced_deadline = time.monotonic() + deadline

    def force_resize(self, target_slots: int,
                     deadline: Optional[float] = None) -> None:
        """Local/test hook: behave as if the scheduler offered a resize to
        `target_slots` (with `deadline` seconds of grace when given)."""
        self._forced_resize = int(target_slots)
        self.force(deadline=deadline)

    def reset(self) -> None:
        """Re-arm after an in-process resize: the old signal was consumed
        (the trial resharded and kept running), so clear the flags and
        resume watching for the next one."""
        self._forced = False
        self._forced_deadline = None
        self._forced_resize = None
        if self._watcher is not None:
            self._watcher.close()
            self._watcher = None
        if self._session is not None and self._allocation_id and (
            self._dist is None or self._dist.is_chief
        ):
            self._watcher = _PreemptionWatcher(
                self._session, self._allocation_id)
            self._watcher.start()

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.close()
