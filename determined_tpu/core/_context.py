"""core.init() — one context bundling all training services.

Reference: harness/determined/core/_context.py:190-320. Two modes:

  - **managed**: launched by an agent; ClusterInfo comes from DET_* env, a
    Session talks to the master, preemption/searcher/metrics are live.
  - **local**: no master; metrics accumulate in-memory, the searcher yields a
    single op of `max_length`, checkpoints go to a local directory. The same
    user code runs in both (reference "train anywhere" semantics).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

from determined_tpu._info import ClusterInfo, get_cluster_info
from determined_tpu.common.api import Session
from determined_tpu.common.trace import Tracer
from determined_tpu.core._checkpoint import CheckpointContext
from determined_tpu.core._distributed import DistributedContext
from determined_tpu.core._preempt import PreemptContext
from determined_tpu.core._profiler import ProfilerContext
from determined_tpu.core._searcher import SearcherContext
from determined_tpu.core._train import TrainContext
from determined_tpu.storage import from_config as storage_from_config

logger = logging.getLogger("determined_tpu.core")


class Context:
    def __init__(
        self,
        train: TrainContext,
        searcher: SearcherContext,
        checkpoint: CheckpointContext,
        preempt: PreemptContext,
        distributed: DistributedContext,
        profiler: ProfilerContext,
        info: Optional[ClusterInfo] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.train = train
        self.searcher = searcher
        self.checkpoint = checkpoint
        self.preempt = preempt
        self.distributed = distributed
        self.profiler = profiler
        self.info = info
        # Lifecycle tracing (docs/observability.md): chief-only emitter,
        # buffered, flushed with metrics. Never None — local mode buffers
        # into tracer.local_spans so instrumented code needs no guards.
        self.tracer = tracer if tracer is not None else Tracer()

    @property
    def hparams(self) -> Dict[str, Any]:
        return self.info.trial.hparams if (self.info and self.info.trial) else {}

    @property
    def trial_seed(self) -> int:
        return self.info.trial.trial_seed if (self.info and self.info.trial) else 0

    @property
    def latest_checkpoint(self) -> Optional[str]:
        return self.info.trial.latest_checkpoint if (self.info and self.info.trial) else None

    def close(self) -> None:
        # Order matters (reference _context.py:79-118): drain checkpoint
        # writes first, final tensorboard sync, then stop watchers, then
        # tear down distributed. The tracer flushes after the checkpoint
        # drain so phase-2 commit spans make the final batch.
        self.checkpoint.close()
        self.tracer.close()
        if getattr(self.train, "_tb", None) is not None:
            self.train._tb.close()
        self.profiler.close()
        self.preempt.close()
        self.distributed.shutdown()

    def __enter__(self) -> "Context":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache (SURVEY hard part b): the agent
    injects DET_XLA_CACHE_DIR (one dir per host, shared across trials),
    so identical-shape ASHA rung trials skip retrace+compile — on real
    v5e sub-slices recompilation is the dominant per-trial overhead.
    min_compile_time 0: rung trials are many and SMALL; the default 1s
    floor would skip exactly the compiles ASHA repeats most."""
    cache_dir = os.environ.get("DET_XLA_CACHE_DIR", "")
    if not cache_dir:
        return
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # Bounded: long-lived hosts accumulate one entry per distinct
        # program forever otherwise (jax only evicts when max_size set).
        max_bytes = int(os.environ.get(
            "DET_XLA_CACHE_MAX_BYTES", str(4 << 30)))
        jax.config.update("jax_compilation_cache_max_size", max_bytes)
    except Exception:
        logger.debug("compilation cache unavailable", exc_info=True)


def init(
    *,
    max_length: Optional[int] = None,
    storage_config: Optional[Dict[str, Any]] = None,
    checkpoint_dir: str = "/tmp/determined_tpu/checkpoints",
    distributed: Optional[DistributedContext] = None,
    async_checkpointing: bool = True,
) -> Context:
    """Bring up the Core API. Managed vs local is auto-detected from env."""
    _enable_compilation_cache()
    info = get_cluster_info()

    if distributed is None:
        if info and info.rendezvous and info.rendezvous.num_hosts > 1:
            distributed = DistributedContext.from_allocation(
                coordinator_addr=info.rendezvous.coordinator_addr
                or info.rendezvous.container_addrs[0] + ":8476",
                num_processes=info.rendezvous.num_hosts,
                process_id=info.rendezvous.container_rank,
            )
        else:
            distributed = DistributedContext.local()

    session: Optional[Session] = None
    trial_id, run_id, allocation_id = 0, 0, None
    if info is not None:
        # Every state-mutating call from this context carries the fencing
        # epoch the master minted for THIS allocation run: after a
        # partition-driven reassignment bumps the run, a zombie of the old
        # run gets a 409 instead of corrupting the successor's lineage
        # (docs/cluster-ops.md "Leases, fencing & split-brain").
        fence_headers = (
            {"X-Allocation-Epoch": str(info.allocation_epoch)}
            if info.allocation_epoch is not None
            else None
        )
        session = Session(info.master_url, info.session_token,
                          headers=fence_headers)
        allocation_id = info.allocation_id
        if info.trial is not None:
            trial_id = info.trial.trial_id
            run_id = info.trial.run_id
        if info.trial and info.trial.config.get("checkpoint_storage"):
            storage_config = storage_config or info.trial.config["checkpoint_storage"]

    storage = storage_from_config(storage_config, default_base=checkpoint_dir)

    # Per-trial tfevents written locally + synced into checkpoint storage
    # (reference tensorboard/base.py async upload thread); chief only.
    tb_manager = None
    if info is not None and info.trial is not None and (
        distributed is None or distributed.is_chief
    ):
        from determined_tpu.tensorboard import TensorboardManager

        try:
            tb_manager = TensorboardManager(
                storage, info.trial.experiment_id, info.trial.trial_id
            )
        except Exception:
            logger.debug("tensorboard manager unavailable", exc_info=True)

    train = TrainContext(
        session,
        trial_id=trial_id,
        run_id=run_id,
        distributed=distributed,
        tensorboard_manager=tb_manager,
    )
    searcher = SearcherContext(
        session,
        trial_id=trial_id,
        distributed=distributed,
        local_max_length=max_length,
    )
    checkpoint = CheckpointContext(
        session,
        storage,
        trial_id=trial_id,
        allocation_id=allocation_id,
        distributed=distributed,
        async_save=async_checkpointing,
    )
    preempt = PreemptContext(session, allocation_id=allocation_id, distributed=distributed)
    profiler = ProfilerContext(train)
    # Span emitter: chief-only (non-chief ranks would duplicate every
    # phase span), trace id from DET_TRACE_ID (minted by the master at
    # trial submit; local mode mints its own so the same instrumentation
    # is inspectable without a cluster).
    is_chief = distributed is None or distributed.is_chief
    tracer = Tracer(
        session if is_chief else None,
        trial_id=trial_id,
        enabled=None if is_chief else False,
    )
    checkpoint.tracer = tracer  # phase-1/phase-2 commit spans
    ctx = Context(train, searcher, checkpoint, preempt, distributed,
                  profiler, info, tracer=tracer)
    if session is not None:
        try:
            session.post(f"/api/v1/trials/{trial_id}/run_prepare", body={})
        except Exception:
            logger.debug("run_prepare failed", exc_info=True)
    return ctx
