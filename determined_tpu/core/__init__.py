"""Core API — framework-agnostic training services (reference
harness/determined/core/)."""

from determined_tpu.core._checkpoint import CheckpointContext, state_id_step  # noqa: F401
from determined_tpu.core._integrity import CorruptCheckpoint  # noqa: F401
from determined_tpu.core._context import Context, init  # noqa: F401
from determined_tpu.core._distributed import DistributedContext  # noqa: F401
from determined_tpu.core._preempt import PreemptContext  # noqa: F401
from determined_tpu.core._profiler import ProfilerContext  # noqa: F401
from determined_tpu.core._searcher import SearcherContext, SearcherOperation  # noqa: F401
from determined_tpu.core._train import TrainContext  # noqa: F401
