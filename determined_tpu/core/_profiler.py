"""ProfilerContext — system metrics + jax.profiler traces.

Reference: harness/determined/core/_profiler.py:23 (pynvml GPU collectors).
TPU re-design: per-host collector thread samples
  - TPU device memory (HBM) via jax.local_devices()[i].memory_stats()
  - host CPU/mem via /proc (no psutil dependency)
and ships them as metrics through TrainContext. `trace()` wraps a step range
in a jax.profiler trace written to the TensorBoard dir (the XLA-native
replacement for torch.profiler pass-through, reference _trainer.py:34).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("determined_tpu.core")


def _read_proc_stat() -> tuple:
    with open("/proc/stat") as f:
        parts = f.readline().split()[1:8]
    vals = [int(p) for p in parts]
    idle = vals[3] + vals[4]
    return sum(vals), idle


def _read_meminfo() -> Dict[str, int]:
    out = {}
    with open("/proc/meminfo") as f:
        for line in f:
            k, v = line.split(":", 1)
            out[k] = int(v.strip().split()[0]) * 1024
    return out


def collect_system_metrics() -> Dict[str, Any]:
    metrics: Dict[str, Any] = {}
    try:
        mem = _read_meminfo()
        metrics["host_mem_used_bytes"] = mem["MemTotal"] - mem.get("MemAvailable", 0)
        metrics["host_mem_total_bytes"] = mem["MemTotal"]
    except Exception:
        pass
    try:
        import jax

        for i, d in enumerate(jax.local_devices()):
            stats = d.memory_stats() or {}
            if "bytes_in_use" in stats:
                metrics[f"tpu{i}_hbm_used_bytes"] = stats["bytes_in_use"]
            if "bytes_limit" in stats:
                metrics[f"tpu{i}_hbm_total_bytes"] = stats["bytes_limit"]
    except Exception:
        pass
    return metrics


# bf16 peak FLOP/s per chip by jax device_kind — used for the
# device-utilization (MFU) series. SURVEY §5 asks for TPU duty-cycle/MXU
# utilization in the profiler pipeline; on TPU the sound training-time
# utilization measure is model-FLOPs utilization (achieved/peak), which
# needs no hardware counters.
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_device() -> Optional[float]:
    try:
        import jax

        kind = jax.local_devices()[0].device_kind
    except Exception:
        return None
    for name, peak in PEAK_BF16_FLOPS.items():
        if kind.startswith(name):
            return peak
    return None


class _Collector(threading.Thread):
    def __init__(self, train_context, interval: float, get_step, profiler):
        super().__init__(daemon=True, name="profiler-collector")
        self._train = train_context
        self._interval = interval
        self._get_step = get_step
        self._profiler = profiler
        # NOT named `_stop`: that would shadow threading.Thread._stop and
        # make join() blow up (the same bug class as the PR-5
        # _PreemptionWatcher fix).
        self._stop_event = threading.Event()

    def run(self) -> None:
        prev = None
        while not self._stop_event.wait(self._interval):
            m = collect_system_metrics()
            try:
                total, idle = _read_proc_stat()
                if prev is not None:
                    dt, di = total - prev[0], idle - prev[1]
                    if dt > 0:
                        m["host_cpu_util"] = 1.0 - di / dt
                prev = (total, idle)
            except Exception:
                pass
            m.update(self._profiler._utilization_window())
            try:
                self._train.report_metrics("profiling", self._get_step(), m)
            except Exception:
                logger.debug("profiler report failed", exc_info=True)

    def close(self) -> None:
        self._stop_event.set()


class ProfilerContext:
    def __init__(self, train_context, tensorboard_dir: Optional[str] = None):
        self._train = train_context
        self._collector: Optional[_Collector] = None
        self._step = 0
        self.tensorboard_dir = tensorboard_dir or os.environ.get(
            "DET_TENSORBOARD_PATH", "/tmp/determined_tpu/tb"
        )
        # device-utilization series (MFU): the Trainer feeds step counts +
        # wall time; the trial declares its FLOPs per optimizer step.
        self._lock = threading.Lock()
        self._flops_per_step: Optional[float] = None
        self._window_steps = 0
        self._window_seconds = 0.0
        self._n_devices = 1
        self._peak = peak_flops_per_device()
        # input-pipeline gauges (fed by the Trainer from DevicePrefetcher
        # window sums): how long each step waited on input, how long the
        # H2D copy took, and how full the prefetch queue ran.
        self._input_wait_ms = 0.0
        self._input_h2d_ms = 0.0
        self._input_depth = 0.0
        self._input_batches = 0
        self._collector_interval = 5.0
        self._trace_active = False

    def set_step(self, step: int) -> None:
        self._step = step

    def set_flops_per_step(self, flops: Optional[float],
                           n_devices: int = 1) -> None:
        """Model FLOPs per (global) optimizer step; enables the
        device_flops_util series (achieved / bf16-peak per chip)."""
        self._flops_per_step = flops
        self._n_devices = max(1, n_devices)

    def observe_steps(self, n_steps: int, seconds: float) -> None:
        """Called by the Trainer each metric flush with the window's step
        count and wall time."""
        with self._lock:
            self._window_steps += n_steps
            self._window_seconds += seconds

    def observe_input(self, wait_ms_sum: float, h2d_ms_sum: float,
                      depth_sum: float, n_batches: int) -> None:
        """Called by the Trainer each metric flush with the input
        pipeline's window sums (DevicePrefetcher.window_sums)."""
        if not n_batches:
            return
        with self._lock:
            self._input_wait_ms += wait_ms_sum
            self._input_h2d_ms += h2d_ms_sum
            self._input_depth += depth_sum
            self._input_batches += n_batches

    def _utilization_window(self) -> Dict[str, Any]:
        with self._lock:
            steps, secs = self._window_steps, self._window_seconds
            self._window_steps, self._window_seconds = 0, 0.0
            in_wait, in_h2d = self._input_wait_ms, self._input_h2d_ms
            in_depth, in_n = self._input_depth, self._input_batches
            self._input_wait_ms = self._input_h2d_ms = 0.0
            self._input_depth, self._input_batches = 0.0, 0
        out: Dict[str, Any] = {}
        if in_n:
            out["input_wait_ms"] = in_wait / in_n
            out["h2d_ms"] = in_h2d / in_n
            out["prefetch_queue_depth"] = in_depth / in_n
        if steps and secs > 0:
            sps = steps / secs
            out["steps_per_second"] = sps
            if self._flops_per_step and self._peak:
                out["device_flops_util"] = (
                    self._flops_per_step * sps / (self._peak * self._n_devices)
                )
        return out

    def on(self, sampling_interval: float = 5.0) -> None:
        if self._collector is None:
            self._collector_interval = sampling_interval
            self._collector = _Collector(
                self._train, sampling_interval, lambda: self._step, self
            )
            self._collector.start()

    def off(self) -> None:
        if self._collector is not None:
            collector = self._collector
            self._collector = None
            collector.close()
            # Bounded join: the collector sleeps up to one interval, and a
            # wedged report must not hold close()/Context.close() hostage.
            collector.join(timeout=self._collector_interval + 2.0)
            if collector.is_alive():
                logger.warning("profiler collector did not stop in time")

    @contextlib.contextmanager
    def trace(self, name: str = "train_step"):
        """jax.profiler trace for a region → TensorBoard trace viewer.

        Hardened (docs/observability.md): re-entry is refused without
        touching the profiler (a nested start_trace would wedge it), a
        failed start logs and runs the body untraced, and stop_trace is
        always attempted so a failure mid-body can't leave the profiler
        stuck for every later trace() call.
        """
        if self._trace_active:
            logger.warning(
                "profiler.trace(%s): a trace is already active; running "
                "untraced (jax.profiler does not nest)", name)
            yield
            return
        import jax

        started = False
        try:
            os.makedirs(self.tensorboard_dir, exist_ok=True)
            jax.profiler.start_trace(self.tensorboard_dir)
            started = True
        except Exception:
            # Profiler unavailability must not fail training: log, run
            # the body untraced.
            logger.warning("profiler.trace(%s): start_trace failed; "
                           "running untraced", name, exc_info=True)
        self._trace_active = started
        try:
            yield
        finally:
            self._trace_active = False
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    logger.warning("profiler.trace(%s): stop_trace failed",
                                   name, exc_info=True)

    def close(self) -> None:
        self.off()
