"""SearcherContext — the trial side of HP search (reference
harness/determined/core/_searcher.py:131).

`operations()` yields `SearcherOperation`s: "train until `length`, then report
the searcher metric". Master mode polls
`GET /api/v1/trials/{id}/searcher/operation` and completes ops via
`POST .../searcher/completed_operation` (reference api_trials.go:1299 →
experiment.TrialCompleteOperation); local mode synthesises a single op of
`local_max_length` so the same loop runs without a master.
"""

from __future__ import annotations

import logging
import time
from typing import Iterator, Optional

from determined_tpu.common.api import Session

logger = logging.getLogger("determined_tpu.core")


class SearcherOperation:
    def __init__(self, context: "SearcherContext", length: int, completed: bool = False):
        self._context = context
        self.length = length  # cumulative units (batches) to train to
        self._completed = completed

    @property
    def completed(self) -> bool:
        return self._completed

    def report_completed(self, searcher_metric: float) -> None:
        if self._completed:
            raise RuntimeError("operation already completed")
        self._completed = True
        self._context._complete_operation(self, searcher_metric)


class SearcherContext:
    def __init__(
        self,
        session: Optional[Session],
        trial_id: int = 0,
        distributed=None,
        local_max_length: Optional[int] = None,
        poll_interval: float = 2.0,
    ):
        self._session = session
        self._trial_id = trial_id
        self._dist = distributed
        self._local_max_length = local_max_length
        self._poll_interval = poll_interval
        self._idle_grace = 15.0  # seconds holding the slice waiting for an op
        self.completed_metrics: list = []  # local mode record

    # -- master interaction (chief only; workers follow via broadcast) --

    def _get_next_op(self, last_length: int) -> dict:
        """Long-poll the master for the next op after `last_length`.

        Returns {"op": {"length": N}}, {"done": true}, or {"idle": true}.

        The idle case is TPU-specific: an ASHA trial paused in its rung (not
        yet promoted, not yet closed — reference asha.go promotionsAsync
        semantics) must RELEASE its slice rather than hold an idle ICI mesh,
        so after a grace window with no op the trial exits cleanly and the
        master re-allocates it if a promotion arrives later.
        """
        assert self._session is not None
        deadline = time.time() + self._idle_grace
        while True:
            resp = self._session.get(
                f"/api/v1/trials/{self._trial_id}/searcher/operation",
                params={"last": last_length, "timeout_seconds": 10},
                timeout=40.0,
            )
            if resp and (resp.get("done") or resp.get("op")):
                return resp
            if time.time() >= deadline:
                return {"idle": True}
            time.sleep(self._poll_interval)

    def _complete_operation(self, op: SearcherOperation, metric: float) -> None:
        if self._session is None:
            self.completed_metrics.append((op.length, metric))
            return
        if self._dist is None or self._dist.is_chief:
            # idempotent: replaying a completed-op report would pop the
            # next pending op and advance the searcher twice.
            self._session.post(
                f"/api/v1/trials/{self._trial_id}/searcher/completed_operation",
                body={"length": op.length, "searcher_metric": float(metric)},
                idempotent=True,
            )

    def operations(self, auto_ack: bool = True) -> Iterator[SearcherOperation]:
        """Yield ops until the searcher closes the trial.

        Multi-host: only the chief talks to the master; op lengths are
        broadcast so all hosts run identical step counts (keeps every host's
        jitted loop in lockstep — a divergent host would hang collectives).
        """
        if self._session is None:
            length = self._local_max_length
            if length is None:
                raise RuntimeError(
                    "local mode needs local_max_length (pass max_length to init())"
                )
            yield SearcherOperation(self, length)
            return

        last_length = 0
        while True:
            if self._dist is None or self._dist.is_chief:
                resp = self._get_next_op(last_length)
                if resp.get("done") or resp.get("idle"):
                    payload = -1
                else:
                    payload = int(resp["op"]["length"])
            else:
                payload = -1
            if self._dist is not None and self._dist.size > 1:
                payload = int(self._dist.broadcast(payload))
            if payload < 0:
                return
            yield SearcherOperation(self, payload)
            last_length = payload
