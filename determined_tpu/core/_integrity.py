"""Checkpoint integrity — the two-phase commit protocol.

The platform's whole fault-tolerance story ("restart from the latest
checkpoint") is only as good as the checkpoint it restarts from: a process
killed mid-async-save leaves a directory that *looks* like a checkpoint but
is missing shards, and nothing in the seed verified any of it. CheckFreq
(FAST '21) separates the snapshot from its durability commit; we adopt the
same shape:

  1. orbax/tensorstore writes the array shards (phase 1, possibly async);
  2. after the save is durable, a ``manifest.json`` records every file's
     size + sha256 (tmp-write + rename, so it is itself atomic);
  3. a ``COMMIT`` marker (tmp-write + rename) is the single atomic bit that
     flips the checkpoint from PARTIAL to COMPLETED.

Restore verifies the other direction: a missing COMMIT (crash between
phases) or a manifest mismatch (torn write, bit rot, truncation) raises the
typed :class:`CorruptCheckpoint`, which the Trainer treats as "walk the
lineage back to the last good checkpoint" — never as "start fresh".

Checkpoints written before this protocol existed (no manifest AND no
COMMIT) verify as legacy: restore proceeds, integrity unknown. A manifest
without a COMMIT, or vice versa, is always corrupt.

Chaos fault points (docs/chaos.md):
  ``checkpoint.write.truncate``  truncate the largest data file after the
                                 manifest is written — models a torn/partial
                                 shard write that the COMMIT raced past
  ``checkpoint.commit.drop``     skip the COMMIT marker — models a crash
                                 between phase 1 and phase 2
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, Optional

from determined_tpu.common import faultpoint

logger = logging.getLogger("determined_tpu.core")

MANIFEST_FILE = "manifest.json"
COMMIT_FILE = "COMMIT"

FAULT_WRITE_TRUNCATE = "checkpoint.write.truncate"
FAULT_COMMIT_DROP = "checkpoint.commit.drop"

# Files that are part of the protocol itself, never of the manifest.
_PROTOCOL_FILES = (MANIFEST_FILE, COMMIT_FILE)


class CorruptCheckpoint(RuntimeError):
    """A checkpoint that exists but must not be restored from.

    Raised on integrity verification failure: missing COMMIT marker
    (interrupted commit), missing/unreadable manifest, or file
    size/checksum mismatch. Distinct from FileNotFoundError (checkpoint
    gone entirely) so callers can treat both as "fall back through the
    lineage" while still re-raising genuine programming errors.
    """

    def __init__(self, storage_id: str, reason: str):
        super().__init__(f"checkpoint {storage_id!r} failed integrity "
                         f"verification: {reason}")
        self.storage_id = storage_id
        self.reason = reason


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    """tmp-write + fsync + rename: the file either exists complete or not
    at all — a crash can never leave a half-written protocol file."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _walk_files(path: str) -> Dict[str, str]:
    """rel path -> abs path for every non-protocol file under `path`."""
    out: Dict[str, str] = {}
    for root, _, files in os.walk(path):
        for f in files:
            full = os.path.join(root, f)
            rel = os.path.relpath(full, path)
            if rel in _PROTOCOL_FILES or rel.endswith(".tmp"):
                continue
            out[rel] = full
    return out


def build_manifest(path: str, checksums: bool = True) -> Dict:
    """Manifest of every file under `path` (sizes, and sha256 when
    `checksums`). Remote backends that can only list sizes pass
    checksums=False and get presence/size verification."""
    files: Dict[str, Dict] = {}
    for rel, full in sorted(_walk_files(path).items()):
        entry: Dict = {"size": os.path.getsize(full)}
        if checksums:
            entry["sha256"] = _sha256(full)
        files[rel] = entry
    return {"version": 1, "files": files}


def commit(path: str, storage_id: str) -> None:
    """Phase 2: write manifest.json then the COMMIT marker, both atomic.

    Must only be called after the phase-1 save is durable (the caller's
    ``wait()``). The ordering is the protocol: a COMMIT implies a valid
    manifest implies verified data.
    """
    manifest = build_manifest(path)
    _atomic_write(
        os.path.join(path, MANIFEST_FILE),
        json.dumps(manifest, sort_keys=True).encode(),
    )

    if faultpoint.fire(FAULT_WRITE_TRUNCATE) is not faultpoint.Action.NONE:
        # Torn-write chaos: corrupt the largest data file AFTER its
        # checksum was recorded, so only integrity verification — not the
        # happy path — can catch it.
        files = _walk_files(path)
        if files:
            victim = max(files.values(), key=os.path.getsize)
            size = os.path.getsize(victim)
            with open(victim, "r+b") as f:
                f.truncate(max(0, size // 2))
            logger.error("faultpoint: %s truncated %s (%d -> %d bytes)",
                         FAULT_WRITE_TRUNCATE, victim, size, size // 2)

    if faultpoint.fire(FAULT_COMMIT_DROP) is not faultpoint.Action.NONE:
        logger.error("faultpoint: %s dropped COMMIT for %s",
                     FAULT_COMMIT_DROP, storage_id)
        return

    _atomic_write(
        os.path.join(path, COMMIT_FILE),
        json.dumps({"storage_id": storage_id,
                    "n_files": len(manifest["files"])}).encode(),
    )


def verify(path: str, storage_id: str) -> bool:
    """Verify a local checkpoint directory against its manifest.

    Returns True when verified, False for legacy checkpoints (written
    before the protocol existed — no manifest AND no COMMIT). Raises
    CorruptCheckpoint on any integrity failure.
    """
    manifest_path = os.path.join(path, MANIFEST_FILE)
    commit_path = os.path.join(path, COMMIT_FILE)
    has_manifest = os.path.exists(manifest_path)
    has_commit = os.path.exists(commit_path)
    if not has_manifest and not has_commit:
        logger.warning(
            "checkpoint %s predates the integrity protocol (no manifest); "
            "restoring unverified", storage_id)
        return False
    if not has_commit:
        raise CorruptCheckpoint(
            storage_id, "no COMMIT marker — the save never finished "
            "committing (process died between write and commit)")
    if not has_manifest:
        raise CorruptCheckpoint(storage_id, "COMMIT present but manifest "
                                "missing")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CorruptCheckpoint(storage_id, f"unreadable manifest: {e}")
    verify_against_manifest(path, manifest, storage_id)
    return True


def verify_against_manifest(path: str, manifest: Dict,
                            storage_id: str) -> None:
    """Check every manifest entry: present, right size, right sha256."""
    files = manifest.get("files")
    if not isinstance(files, dict):
        raise CorruptCheckpoint(storage_id, "manifest has no file table")
    for rel, entry in files.items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            raise CorruptCheckpoint(storage_id, f"missing file {rel!r}")
        size = os.path.getsize(full)
        if size != entry.get("size"):
            raise CorruptCheckpoint(
                storage_id, f"size mismatch for {rel!r}: manifest says "
                f"{entry.get('size')}, found {size}")
        want = entry.get("sha256")
        if want and _sha256(full) != want:
            raise CorruptCheckpoint(storage_id,
                                    f"checksum mismatch for {rel!r}")


def verify_listing(listing: Dict[str, int], manifest: Optional[Dict],
                   storage_id: str) -> bool:
    """Presence/size verification from a remote file listing (rel -> size),
    for backends where downloading every shard just to checksum it would
    defeat the point. Same legacy/corrupt semantics as `verify`."""
    has_commit = COMMIT_FILE in listing
    has_manifest = MANIFEST_FILE in listing
    if not has_commit and not has_manifest:
        logger.warning(
            "checkpoint %s predates the integrity protocol (no manifest); "
            "restoring unverified", storage_id)
        return False
    if not has_commit:
        raise CorruptCheckpoint(
            storage_id, "no COMMIT marker — the save never finished "
            "committing (process died between write and commit)")
    if manifest is None:
        raise CorruptCheckpoint(storage_id, "COMMIT present but manifest "
                                "missing or unreadable")
    files = manifest.get("files")
    if not isinstance(files, dict):
        raise CorruptCheckpoint(storage_id, "manifest has no file table")
    for rel, entry in files.items():
        if rel not in listing:
            raise CorruptCheckpoint(storage_id, f"missing file {rel!r}")
        if listing[rel] != entry.get("size"):
            raise CorruptCheckpoint(
                storage_id, f"size mismatch for {rel!r}: manifest says "
                f"{entry.get('size')}, found {listing[rel]}")
    return True
