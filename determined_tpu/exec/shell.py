"""Shell NTSC task entrypoint.

Reference: `det shell` runs sshd in the task container and tunnels ssh over
the master's TCP proxy (master/internal/proxy/tcp.go + cli/tunnel.py). The
TPU-VM design has no container/sshd; instead the task runs this small TCP
shell server and the CLI reaches it through the master's `det-tcp` tunnel
(`/proxy/{task_id}/` with `Upgrade: det-tcp`).

Protocol per connection: all received bytes go to a fresh `/bin/sh -s`
stdin; its stdout+stderr stream back. Half-close (client shutdown WR) ends
stdin, the shell exits, output drains, connection closes — which makes
one-shot `det shell run <id> <cmd>` a clean round-trip. Interactive use
(`det shell open`) bridges the user's terminal over the same stream.
"""

from __future__ import annotations

import hmac
import logging
import os
import socket
import subprocess
import sys
import threading

from determined_tpu.exec._util import free_port, report_proxy_address

logger = logging.getLogger("determined_tpu.exec.shell")

# Connections must lead with this secret (master_agents.cc injects it and
# the master's det-tcp tunnel prepends it after its can_edit check); the
# server binds 0.0.0.0 so the task's peers can be on other hosts, and
# without the handshake anyone with network reach could run commands as
# the task owner.
_SECRET = os.environ.get("DET_PROXY_SECRET", "")


def _read_handshake(conn: socket.socket, max_len: int = 256) -> tuple[bool, bytes]:
    """Read up to the first newline; return (ok, residual-after-newline)."""
    buf = b""
    while b"\n" not in buf:
        if len(buf) > max_len:
            return False, b""
        data = conn.recv(4096)
        if not data:
            return False, b""
        buf += data
    line, _, residual = buf.partition(b"\n")
    ok = hmac.compare_digest(line.strip(), _SECRET.encode())
    return ok, residual


def _serve_client(conn: socket.socket) -> None:
    with conn:
        if _SECRET:
            # Pre-auth deadline: an unauthenticated client that connects
            # and sends nothing must not pin a thread + fd forever.
            conn.settimeout(15)
            try:
                ok, residual = _read_handshake(conn)
            except OSError:
                return
            if not ok:
                logger.warning("refusing connection: bad proxy secret")
                return
            conn.settimeout(None)
        else:
            residual = b""
        proc = subprocess.Popen(
            ["/bin/sh", "-s"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )

        def feed_stdin() -> None:
            try:
                if residual:
                    proc.stdin.write(residual)
                    proc.stdin.flush()
                while True:
                    data = conn.recv(65536)
                    if not data:
                        break
                    proc.stdin.write(data)
                    proc.stdin.flush()
            except (OSError, ValueError):
                pass
            try:
                proc.stdin.close()
            except OSError:
                pass

        t = threading.Thread(target=feed_stdin, daemon=True)
        t.start()
        try:
            while True:
                out = proc.stdout.read1(65536)
                if not out:
                    break
                conn.sendall(out)
        except OSError:
            pass
        proc.wait()


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    port = free_port()
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", port))
    srv.listen(16)
    if not _SECRET:
        # Should only happen under a pre-handshake master; the downgrade
        # to unauthenticated remote command execution must be loud.
        logger.warning(
            "DET_PROXY_SECRET not set: serving UNAUTHENTICATED shell on "
            "0.0.0.0 — anyone with network reach can run commands")
    addr = f"tcp://{socket.gethostname()}:{port}"
    report_proxy_address(addr)
    logger.info("shell server at %s", addr)
    print(f"shell server listening on {addr}", flush=True)
    while True:
        conn, _ = srv.accept()
        threading.Thread(target=_serve_client, args=(conn,),
                         daemon=True).start()


if __name__ == "__main__":
    sys.exit(main())
