"""Shell NTSC task entrypoint.

Reference: `det shell` runs sshd in the task container and tunnels ssh over
the master's TCP proxy (master/internal/proxy/tcp.go + cli/tunnel.py). The
TPU-VM design has no container/sshd; instead the task runs this small TCP
shell server and the CLI reaches it through the master's `det-tcp` tunnel
(`/proxy/{task_id}/` with `Upgrade: det-tcp`).

Protocol per connection: all received bytes go to a fresh `/bin/sh -s`
stdin; its stdout+stderr stream back. Half-close (client shutdown WR) ends
stdin, the shell exits, output drains, connection closes — which makes
one-shot `det shell run <id> <cmd>` a clean round-trip. Interactive use
(`det shell open`) bridges the user's terminal over the same stream.
"""

from __future__ import annotations

import logging
import socket
import subprocess
import sys
import threading

from determined_tpu.exec._util import free_port, report_proxy_address

logger = logging.getLogger("determined_tpu.exec.shell")


def _serve_client(conn: socket.socket) -> None:
    with conn:
        proc = subprocess.Popen(
            ["/bin/sh", "-s"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )

        def feed_stdin() -> None:
            try:
                while True:
                    data = conn.recv(65536)
                    if not data:
                        break
                    proc.stdin.write(data)
                    proc.stdin.flush()
            except (OSError, ValueError):
                pass
            try:
                proc.stdin.close()
            except OSError:
                pass

        t = threading.Thread(target=feed_stdin, daemon=True)
        t.start()
        try:
            while True:
                out = proc.stdout.read1(65536)
                if not out:
                    break
                conn.sendall(out)
        except OSError:
            pass
        proc.wait()


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    port = free_port()
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", port))
    srv.listen(16)
    addr = f"tcp://{socket.gethostname()}:{port}"
    report_proxy_address(addr)
    logger.info("shell server at %s", addr)
    print(f"shell server listening on {addr}", flush=True)
    while True:
        conn, _ = srv.accept()
        threading.Thread(target=_serve_client, args=(conn,),
                         daemon=True).start()


if __name__ == "__main__":
    sys.exit(main())
