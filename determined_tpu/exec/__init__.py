"""Task-container bootstrap shims (reference: harness/determined/exec/)."""
