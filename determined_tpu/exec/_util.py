"""Shared helpers for NTSC task entrypoints."""

from __future__ import annotations

import logging
import os
import socket

from determined_tpu.common.api import Session

logger = logging.getLogger("determined_tpu.exec")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def report_proxy_address(addr: str) -> None:
    """Report the serving URL for this allocation to the master
    (PostAllocationProxyAddress analogue); no-op outside a cluster."""
    master = os.environ.get("DET_MASTER")
    allocation_id = os.environ.get("DET_ALLOCATION_ID")
    if not master or not allocation_id:
        return
    try:
        Session(master, os.environ.get("DET_SESSION_TOKEN")).post(
            f"/api/v1/allocations/{allocation_id}/proxy_address",
            body={"rank": 0, "address": addr},
        )
    except Exception:
        logger.warning("failed to report proxy address", exc_info=True)
