"""Checkpoint GC task (reference harness/determined/exec/gc_checkpoints.py,
spawned by master/internal/checkpoint_gc.go:76).

Runs as a zero-slot task on an agent: DET_GC_SPEC (JSON env injected by the
master) names the storage config and the checkpoint uuids outside the
experiment's retention policy. Files are deleted task-side — this is where
the storage credentials live — and each deletion is PATCHed into the
master's checkpoint registry as state DELETED."""

from __future__ import annotations

import json
import logging
import os
import sys

logger = logging.getLogger("determined_tpu.exec.gc")


def main() -> int:
    logging.basicConfig(level=logging.INFO, format="gc: %(message)s")
    spec = json.loads(os.environ.get("DET_GC_SPEC", "{}"))
    uuids = spec.get("uuids", [])
    if not uuids:
        logger.info("nothing to delete")
        return 0

    from determined_tpu.common.api import Session
    from determined_tpu.storage import from_config

    storage = from_config(spec.get("checkpoint_storage"))
    session = None
    master = os.environ.get("DET_MASTER")
    token = os.environ.get("DET_SESSION_TOKEN")
    if master and token:
        session = Session(master, token)

    deleted, failed = [], []
    for uuid in uuids:
        try:
            storage.delete(uuid)
            deleted.append(uuid)
            logger.info("deleted %s", uuid)
        except Exception:
            logger.warning("failed to delete %s", uuid, exc_info=True)
            failed.append(uuid)
            continue
        # Report each deletion as it happens: a crash/restart mid-GC must
        # not leave already-deleted files registered as COMPLETED (the GC
        # task is one-shot — there is no retry for lost bookkeeping).
        if session is not None:
            try:
                session.patch(
                    "/api/v1/checkpoints",
                    body={"checkpoints": [{"uuid": uuid, "state": "DELETED"}]},
                )
            except Exception:
                logger.warning("failed to report deletion of %s", uuid,
                               exc_info=True)
                failed.append(uuid)
    logger.info("done: %d deleted, %d failed", len(deleted), len(failed))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
