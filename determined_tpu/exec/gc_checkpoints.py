"""Checkpoint GC task (reference harness/determined/exec/gc_checkpoints.py,
spawned by master/internal/checkpoint_gc.go:76).

Runs as a zero-slot task on an agent: DET_GC_SPEC (JSON env injected by the
master) names the storage config and the checkpoint uuids outside the
experiment's retention policy. Files are deleted task-side — this is where
the storage credentials live — and each deletion is PATCHed into the
master's checkpoint registry as state DELETED."""

from __future__ import annotations

import json
import logging
import os
import sys

logger = logging.getLogger("determined_tpu.exec.gc")


def main() -> int:
    logging.basicConfig(level=logging.INFO, format="gc: %(message)s")
    spec = json.loads(os.environ.get("DET_GC_SPEC", "{}"))
    uuids = list(spec.get("uuids", []))
    # Stale PARTIAL checkpoints (docs/checkpointing.md): saves whose
    # phase-2 commit never landed, past the master's TTL. The master never
    # includes a trial's newest PARTIAL — an in-flight async save may
    # still be committing it — so everything here is safe to delete.
    partial_uuids = [u for u in spec.get("partial_uuids", [])
                     if u not in set(uuids)]
    if partial_uuids:
        logger.info("%d stale PARTIAL checkpoint(s) past TTL",
                    len(partial_uuids))
    uuids += partial_uuids
    if not uuids:
        logger.info("nothing to delete")
        return 0

    from determined_tpu.common.api import Session
    from determined_tpu.storage import from_config

    storage = from_config(spec.get("checkpoint_storage"))
    session = None
    master = os.environ.get("DET_MASTER")
    token = os.environ.get("DET_SESSION_TOKEN")
    if master and token:
        session = Session(master, token)

    deleted, failed = [], []
    for uuid in uuids:
        try:
            storage.delete(uuid)
            deleted.append(uuid)
            logger.info("deleted %s", uuid)
        except Exception:
            logger.warning("failed to delete %s", uuid, exc_info=True)
            failed.append(uuid)
            continue
        # Report each deletion as it happens: a crash/restart mid-GC must
        # not leave already-deleted files registered as COMPLETED (the GC
        # task is one-shot — there is no retry for lost bookkeeping).
        if session is not None:
            try:
                session.patch(
                    "/api/v1/checkpoints",
                    body={"checkpoints": [{"uuid": uuid, "state": "DELETED"}]},
                )
            except Exception:
                logger.warning("failed to report deletion of %s", uuid,
                               exc_info=True)
                failed.append(uuid)
    logger.info("done: %d deleted, %d failed", len(deleted), len(failed))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
