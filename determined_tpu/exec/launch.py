"""Task entrypoint: prep, then spawn the experiment's entrypoint.

Reference: harness/determined/exec/launch.py:29 (spawn + signal forwarding,
SIGTERM→preemption :49-55) combined with the launch layers under
harness/determined/launch/. The TPU launch model is simpler than
torchrun/horovodrun: ONE process per host owns all local chips, so there is
no per-device process fan-out — the "distributed launcher" reduces to
exporting the jax.distributed coordination env and exec'ing the user
entrypoint.

Exported for multi-host JAX (consumed by determined_tpu.core.init /
user code):
  DET_COORDINATOR_ADDR  chief_host:port  (jax.distributed.initialize)
  DET_NODE_RANK / DET_NUM_NODES          (process_id / num_processes)
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import subprocess
import sys

from determined_tpu.exec import prep as prep_mod

logger = logging.getLogger("determined_tpu.exec")


def build_command(config: Optional[dict] = None) -> list:
    """Resolve the experiment entrypoint into an argv list."""
    import json

    if config is None:
        config = json.loads(os.environ.get("DET_EXPERIMENT_CONFIG", "{}"))
    entrypoint = config.get("entrypoint")
    if entrypoint is None:
        entrypoint = os.environ.get("DET_ENTRYPOINT")
        # Array entrypoints travel as JSON to keep argument boundaries
        # exact (a space-joined string would re-split wrongly).
        if entrypoint and entrypoint.lstrip().startswith("["):
            try:
                entrypoint = json.loads(entrypoint)
            except ValueError:
                pass
    if entrypoint is None:
        raise RuntimeError("no entrypoint in experiment config")
    if isinstance(entrypoint, list):
        return [str(x) for x in entrypoint]
    return shlex.split(str(entrypoint))


def apply_task_environment(env: dict, config: dict) -> dict:
    """Render the expconf `environment:` block into the process env
    (reference: task-spec env/image rendering, master/pkg/tasks/task.go:194-234
    — on TPU-VMs there are no containers, so "environment management" means
    interpreter selection + import paths + env vars):

      environment_variables: ["K=V", ...]   (also applied master-side; done
                                             here too so local mode matches)
      venv: /path/to/venv                    activation-equivalent: VIRTUAL_ENV
                                             + venv/bin first on PATH, so a
                                             `python3 ...` entrypoint resolves
                                             to the task's interpreter
      python_path: [dir, ...]                appended to PYTHONPATH (extra
                                             package roots shipped with the
                                             context or mounted on the host)
    """
    envcfg = config.get("environment") or {}
    # Flat "K": "V" entries are env vars too (master-side rendering does the
    # same; applying here keeps local mode identical).
    for k, v in envcfg.items():
        if k in ("environment_variables", "venv", "python_path"):
            continue
        if isinstance(v, str):
            env[k] = v
    for kv in envcfg.get("environment_variables", []) or []:
        k, sep, v = str(kv).partition("=")
        if sep:
            env[k] = v
    venv = envcfg.get("venv")
    if venv:
        venv = os.path.expanduser(str(venv))
        env["VIRTUAL_ENV"] = venv
        env["PATH"] = os.path.join(venv, "bin") + os.pathsep + env.get("PATH", "")
        env.pop("PYTHONHOME", None)
    for p in envcfg.get("python_path", []) or []:
        env["PYTHONPATH"] = (
            env.get("PYTHONPATH", "") + os.pathsep + os.path.expanduser(str(p))
        ).strip(os.pathsep)
    return env


def main() -> int:
    import json

    logging.basicConfig(level=logging.INFO, format="%(name)s: %(message)s")

    info = prep_mod.prep()
    env = dict(os.environ)
    if info is not None and len(info["container_addrs"]) > 1:
        env["DET_COORDINATOR_ADDR"] = info["coordinator_addr"]
    # Make the extracted context importable.
    workdir = env.get("DET_WORKDIR", os.getcwd())
    env["PYTHONPATH"] = workdir + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("PYTHONUNBUFFERED", "1")
    config = json.loads(os.environ.get("DET_EXPERIMENT_CONFIG", "{}"))
    apply_task_environment(env, config)

    # Virtual-slot devclusters (JAX_PLATFORMS=cpu): make the task's visible
    # JAX device count MATCH its allocated slot count, so the mesh resolves
    # at the size the scheduler granted — on a real TPU-VM the runtime
    # exposes the host's chips and this is a no-op. This is what lets an
    # elastic re-placement at a new size (docs/elasticity.md) actually
    # re-resolve the mesh instead of always seeing one CPU device.
    try:
        slot_ids = json.loads(env.get("DET_SLOT_IDS", "[]"))
    except ValueError:
        slot_ids = []
    if (slot_ids and env.get("JAX_PLATFORMS", "") == "cpu"
            and "xla_force_host_platform_device_count"
            not in env.get("XLA_FLAGS", "")):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={len(slot_ids)}")

    # startup-hook.sh from the context dir runs before the entrypoint
    # (reference exec/prep_container.py + entrypoint.sh: dependency
    # installs, data staging). A failing hook fails the task — running a
    # trial against a half-prepared environment would be worse.
    hook = os.path.join(workdir, "startup-hook.sh")
    if os.path.exists(hook):
        logger.info("running startup-hook.sh")
        rc = subprocess.run(["sh", hook], env=env, cwd=workdir).returncode
        if rc != 0:
            logger.error("startup-hook.sh failed (exit %d)", rc)
            return rc

    cmd = build_command(config)
    logger.info("launching entrypoint: %s", cmd)
    proc = subprocess.Popen(cmd, env=env, cwd=workdir)

    # Forward termination signals so preemption/kill reaches the training
    # process (reference exec/launch.py:49-55).
    def forward(signum, frame):
        try:
            proc.send_signal(signum)
        except ProcessLookupError:
            pass

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)

    return proc.wait()


if __name__ == "__main__":
    sys.exit(main())
