"""Task prep: context download + rendezvous.

Reference: harness/determined/exec/prep_container.py — downloads the user
code tarball (GetTaskContextDirectory), performs rendezvous against the
master (AllocationRendezvousInfo, api_trials.go:1495; master side gathers
addresses in task/rendezvous.go:94), and writes
``$DET_RUN_DIR/info/rendezvous.json`` for later processes to read through
``get_cluster_info()``.

TPU addition: the rendezvous result includes ``coordinator_addr`` — the
chief host plus a fixed port — which ``jax.distributed.initialize`` uses to
form the multi-host runtime over ICI/DCN (SURVEY.md §5 "Distributed
communication backend").
"""

from __future__ import annotations

import base64
import io
import json
import logging
import os
import tarfile
from typing import Optional

from determined_tpu.common.api import Session

logger = logging.getLogger("determined_tpu.exec")

JAX_COORDINATOR_PORT = 12355


def download_context(session: Session, task_id: str, workdir: str) -> None:
    """Extract the experiment's model-def tarball into the workdir."""
    resp = session.get(f"/api/v1/tasks/{task_id}/context")
    b64 = (resp or {}).get("b64_tgz") or ""
    if not b64:
        logger.info("no context directory for task %s", task_id)
        return
    raw = base64.b64decode(b64)
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
        for member in tar.getmembers():
            # refuse path escapes
            target = os.path.realpath(os.path.join(workdir, member.name))
            if not target.startswith(os.path.realpath(workdir)):
                raise RuntimeError(f"unsafe path in context tar: {member.name}")
        tar.extractall(workdir)
    logger.info("extracted context (%d bytes) into %s", len(raw), workdir)


def rendezvous(session: Session, allocation_id: str, run_dir: str) -> dict:
    """Block until every host of the allocation is up; persist the result."""
    resp = session.get(
        f"/api/v1/allocations/{allocation_id}/rendezvous",
        params={"timeout_seconds": 600},
        timeout=630.0,
    )
    addrs = resp["addresses"]
    rank = int(os.environ.get("DET_NODE_RANK", "0"))
    slot_ids = json.loads(os.environ.get("DET_SLOT_IDS", "[]"))
    info = {
        "container_addrs": addrs,
        "container_rank": rank,
        "slot_ids": slot_ids,
        "coordinator_addr": f"{addrs[0]}:{JAX_COORDINATOR_PORT}",
    }
    info_dir = os.path.join(run_dir, "info")
    os.makedirs(info_dir, exist_ok=True)
    with open(os.path.join(info_dir, "rendezvous.json"), "w") as f:
        json.dump(info, f)
    # Chief ip for launch layers (reference exec/prep_container.py exports
    # DET_CHIEF_IP).
    os.environ["DET_CHIEF_IP"] = addrs[0]
    return info


def prep(session: Optional[Session] = None) -> Optional[dict]:
    """Full prep flow; returns rendezvous info (None outside a cluster)."""
    master = os.environ.get("DET_MASTER")
    if not master:
        return None
    session = session or Session(master, os.environ.get("DET_SESSION_TOKEN"))
    workdir = os.environ.get("DET_WORKDIR", os.getcwd())
    run_dir = os.environ.get("DET_RUN_DIR", workdir)
    task_id = os.environ.get("DET_TASK_ID", "")
    allocation_id = os.environ.get("DET_ALLOCATION_ID", "")
    if task_id:
        download_context(session, task_id, workdir)
    if allocation_id:
        return rendezvous(session, allocation_id, run_dir)
    return None
