"""TensorBoard NTSC task entrypoint.

Reference: harness/determined/exec/tensorboard.py — fetch per-trial tfevents
from checkpoint storage, serve them with the tensorboard binary, keep
re-syncing while experiments are live, and report the serving address to the
master (PostAllocationProxyAddress analogue).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import time

from determined_tpu.common.api import Session
from determined_tpu.exec._util import free_port, report_proxy_address
from determined_tpu.storage import from_config as storage_from_config
from determined_tpu.tensorboard import fetch_experiment_logs

logger = logging.getLogger("determined_tpu.exec.tensorboard")


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    master = os.environ.get("DET_MASTER")
    session = Session(master, os.environ.get("DET_SESSION_TOKEN")) if master else None
    exp_ids = json.loads(os.environ.get("DET_EXPERIMENT_IDS", "[]"))
    allocation_id = os.environ.get("DET_ALLOCATION_ID")
    logdir = os.path.abspath("tb_logs")
    os.makedirs(logdir, exist_ok=True)

    storages = {}
    if session is not None:
        for eid in exp_ids:
            config = session.get(f"/api/v1/experiments/{eid}")["experiment"]["config"]
            storages[eid] = storage_from_config(config.get("checkpoint_storage"))

    def sync_all() -> None:
        for eid, storage in storages.items():
            fetch_experiment_logs(storage, eid, logdir)

    sync_all()

    port = int(os.environ.get("TENSORBOARD_PORT", "0")) or free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "tensorboard.main", "--logdir", logdir,
         "--port", str(port), "--host", "0.0.0.0",
         "--reload_interval", "15"],
    )
    addr = f"http://{socket.gethostname()}:{port}"
    logger.info("tensorboard serving %s at %s", exp_ids, addr)
    report_proxy_address(addr)

    try:
        while proc.poll() is None:
            time.sleep(30.0)
            sync_all()
    except KeyboardInterrupt:
        proc.terminate()
    return proc.wait()


if __name__ == "__main__":
    sys.exit(main())
