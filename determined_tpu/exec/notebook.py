"""Notebook NTSC task entrypoint (reference: notebook task container
running jupyter, master/internal/command/). Requires jupyter in the task
environment; reports the server URL as the allocation proxy address."""

from __future__ import annotations

import logging
import socket
import subprocess
import sys

from determined_tpu.exec._util import free_port, report_proxy_address

logger = logging.getLogger("determined_tpu.exec.notebook")


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    try:
        import notebook  # noqa: F401
    except ImportError:
        print(
            "jupyter `notebook` is not installed in this task environment; "
            "install it in the environment image to use notebook tasks",
            file=sys.stderr,
        )
        return 1

    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "notebook", "--ip=0.0.0.0",
         f"--port={port}", "--no-browser", "--allow-root",
         "--NotebookApp.token=", "--NotebookApp.password="],
    )
    addr = f"http://{socket.gethostname()}:{port}"
    report_proxy_address(addr)
    logger.info("notebook at %s", addr)
    return proc.wait()


if __name__ == "__main__":
    sys.exit(main())
