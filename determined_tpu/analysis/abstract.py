"""Engine 1 — abstract trace.

Everything here runs on `jax.eval_shape` / `jax.make_jaxpr`: shapes and
dtypes only, no device buffers, no compiles. A preflight of a 70B-param
trial costs the same few hundred milliseconds as an MNIST one, which is what
lets the master run it inline at experiment create.

Produces:
  - a per-device HBM footprint breakdown (params, optimizer state, grads,
    donation overhead, batch, forward-activation upper bound), each leaf
    divided by the product of the mesh axes its PartitionSpec shards over
  - DTL001 state-not-donated, DTL002 implicit-replication,
    DTL003 batch-mesh-mismatch, DTL004 hbm-over-budget,
    DTL005 abstract-trace-failed
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec

from determined_tpu.analysis.diagnostics import Diagnostic
from determined_tpu.analysis.rules import RULES
from determined_tpu.parallel.mesh import AXIS_ORDER
from determined_tpu.train.state import TrainState

# Leaves at or above this size with no sharded dimension trigger DTL002.
LARGE_LEAF_BYTES = 16 * 1024 * 1024


def _abstract(x: Any) -> Any:
    """Pytree of arrays/scalars → pytree of ShapeDtypeStruct."""

    def one(v):
        arr = np.asarray(v) if not hasattr(v, "shape") else v
        dtype = getattr(arr, "dtype", np.dtype(np.float32))
        return jax.ShapeDtypeStruct(np.shape(arr), dtype)

    return jax.tree_util.tree_map(one, x)


def _leaf_bytes(leaf: Any) -> int:
    return int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize


def _spec_factor(spec: Optional[PartitionSpec], sizes: Dict[str, int]) -> int:
    """How many ways a leaf with this PartitionSpec is split."""
    if spec is None:
        return 1
    factor = 1
    for entry in spec:
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        for a in axes:
            factor *= sizes.get(a, 1)
    return factor


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", str(p))
        parts.append(str(key))
    return "/".join(parts) or "<root>"


def analyze_trial(
    trial: Any,
    n_devices: int,
    batch: Any = None,
    hbm_budget_bytes: Optional[int] = None,
    large_leaf_bytes: int = LARGE_LEAF_BYTES,
    source_file: Optional[str] = None,
    trace_failure_excused: bool = False,
) -> Tuple[List[Diagnostic], Dict[str, Any], List[str]]:
    """Analyze a JaxTrial instance against its declared mesh.

    `batch`: one global batch (arrays or ShapeDtypeStructs); pulled from
    `trial.build_training_data()` when omitted. `trace_failure_excused`
    silences DTL005 when an AST finding (e.g. DTL101) already explains why
    the step cannot trace.

    Returns (diagnostics, hbm breakdown, notes).
    """
    diags: List[Diagnostic] = []
    notes: List[str] = []
    hbm: Dict[str, Any] = {}

    mesh_cfg = trial.mesh_config().resolve(n_devices)
    sizes = dict(zip(AXIS_ORDER, mesh_cfg.sizes()))
    rules = trial.sharding_rules()

    # -- abstract state: params + optimizer state -----------------------
    rng = jax.ShapeDtypeStruct((2,), np.uint32)

    try:
        tx = trial.optimizer()

        def init_state(r):
            params = trial.init_params(r)
            return TrainState(
                step=jax.numpy.zeros((), jax.numpy.int32),
                params=params,
                opt_state=tx.init(params),
            )

        shapes = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    except Exception as e:  # init itself must trace for any HBM analysis
        diags.append(RULES["DTL005"].diag(
            f"state initialization failed to trace abstractly: "
            f"{type(e).__name__}: {e}", file=source_file))
        return diags, hbm, notes

    axes = trial.param_logical_axes()
    if axes is not None:
        from determined_tpu.train.state import param_specs

        pspecs = param_specs(axes, rules)
    else:
        pspecs = jax.tree_util.tree_map(lambda _: PartitionSpec(),
                                        shapes.params)

    flat_params = jax.tree_util.tree_flatten_with_path(shapes.params)[0]
    flat_specs, _ = jax.tree_util.tree_flatten(
        pspecs, is_leaf=lambda x: isinstance(x, PartitionSpec))
    if len(flat_specs) != len(flat_params):
        notes.append(
            "param_logical_axes() structure does not match params; treating "
            "all parameters as replicated")
        flat_specs = [PartitionSpec()] * len(flat_params)

    params_bytes = 0
    params_pd = 0  # per device
    shape_to_factor: Dict[Tuple, int] = {}
    for (path, leaf), spec in zip(flat_params, flat_specs):
        b = _leaf_bytes(leaf)
        factor = _spec_factor(spec, sizes)
        params_bytes += b
        params_pd += b // factor
        shape_to_factor.setdefault((leaf.shape, str(leaf.dtype)), factor)
        if n_devices > 1 and factor == 1 and b >= large_leaf_bytes:
            diags.append(RULES["DTL002"].diag(
                f"parameter '{_path_str(path)}' "
                f"({'x'.join(map(str, leaf.shape))} {leaf.dtype}, "
                f"{b / 2**20:.1f} MiB) has no sharded dimension and is "
                f"replicated on all {n_devices} devices; annotate its "
                "param_logical_axes() (e.g. 'embed'/'vocab') to shard it",
                file=source_file))

    opt_bytes = 0
    opt_pd = 0
    for leaf in jax.tree_util.tree_leaves(shapes.opt_state):
        b = _leaf_bytes(leaf)
        factor = shape_to_factor.get((leaf.shape, str(leaf.dtype)), 1)
        opt_bytes += b
        opt_pd += b // factor

    # Gradients are transient but alive together with params + opt state at
    # the update; they shard like params.
    grads_pd = params_pd

    donated = bool(getattr(trial, "donate_state", True))
    donation_extra_pd = 0 if donated else params_pd + opt_pd
    if not donated:
        diags.append(RULES["DTL001"].diag(
            f"trial sets donate_state=False: the previous step's params + "
            f"optimizer state stay alive across the update "
            f"(+{(params_pd + opt_pd) / 2**20:.1f} MiB/device); set "
            "donate_state=True unless the host reuses the old state",
            file=source_file))

    # -- batch ----------------------------------------------------------
    batch_pd = 0
    abstract_batch = None
    if batch is None:
        try:
            batch = next(iter(trial.build_training_data()))
        except Exception as e:
            notes.append(f"could not draw a batch from "
                         f"build_training_data(): {type(e).__name__}: {e}")
            batch = None
    if batch is not None:
        abstract_batch = _abstract(batch)
        batch_axes = rules.mesh_axes("batch")
        if isinstance(batch_axes, str):
            batch_axes = (batch_axes,)
        bprod = math.prod(sizes.get(a, 1) for a in batch_axes or ())
        bad: List[str] = []
        batch_bytes = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                abstract_batch)[0]:
            batch_bytes += _leaf_bytes(leaf)
            if leaf.shape and leaf.shape[0] % bprod != 0:
                bad.append(f"'{_path_str(path)}' [{leaf.shape[0]}, ...]")
        batch_pd = batch_bytes // max(1, bprod)
        hbm["batch_bytes"] = batch_pd
        if bad:
            diags.append(RULES["DTL003"].diag(
                f"global batch dims {', '.join(bad)} are not divisible by "
                f"the mesh batch axes {tuple(batch_axes)} = {bprod} "
                f"(mesh {dict((a, s) for a, s in sizes.items() if s > 1)}); "
                "pad the loader batch or fix global_batch_size",
                file=source_file))

    # -- forward activations (upper bound, pre-fusion) ------------------
    acts_pd = None
    if abstract_batch is not None:
        try:
            if getattr(trial, "stateful", False):
                extra = _abstract(trial.init_extra())
                jaxpr = jax.make_jaxpr(
                    lambda p, e, b, r: trial.loss(p, e, b, r))(
                        shapes.params, extra, abstract_batch, rng)
            else:
                jaxpr = jax.make_jaxpr(
                    lambda p, b, r: trial.loss(p, b, r))(
                        shapes.params, abstract_batch, rng)
            total = 0
            for eqn in jaxpr.jaxpr.eqns:
                for v in eqn.outvars:
                    aval = getattr(v, "aval", None)
                    if aval is None or not hasattr(aval, "shape"):
                        continue
                    try:
                        itemsize = np.dtype(aval.dtype).itemsize
                    except TypeError:
                        # Extended dtypes (typed PRNG keys etc.) are not
                        # numpy dtypes and are negligible HBM anyway.
                        continue
                    total += int(math.prod(aval.shape)) * itemsize
            batch_axes = rules.mesh_axes("batch")
            if isinstance(batch_axes, str):
                batch_axes = (batch_axes,)
            bprod = math.prod(sizes.get(a, 1) for a in batch_axes or ())
            acts_pd = total // max(1, bprod)
            hbm["activations_upper_bound_bytes"] = acts_pd
        except Exception as e:
            if not trace_failure_excused:
                diags.append(RULES["DTL005"].diag(
                    f"loss failed to trace abstractly "
                    f"({type(e).__name__}: {e}); activation footprint "
                    "unknown — fix the trace error (often a host sync or "
                    "data-dependent Python control flow)",
                    file=source_file))
            else:
                notes.append(
                    "activation estimate unavailable: loss does not trace "
                    "(already reported by an AST rule)")

    hbm.update({
        "params_bytes": params_pd,
        "opt_state_bytes": opt_pd,
        "grads_bytes": grads_pd,
        "donation_extra_bytes": donation_extra_pd,
        "params_total_bytes": params_bytes,
        "opt_state_total_bytes": opt_bytes,
        "mesh": {a: s for a, s in sizes.items()},
        "n_devices": n_devices,
        "donated": donated,
    })
    total_pd = (params_pd + opt_pd + grads_pd + donation_extra_pd + batch_pd)
    hbm["total_bytes"] = total_pd

    if hbm_budget_bytes:
        hbm["budget_bytes"] = int(hbm_budget_bytes)
        if total_pd > hbm_budget_bytes:
            diags.append(RULES["DTL004"].diag(
                f"estimated per-device HBM lower bound "
                f"{total_pd / 2**30:.2f} GiB exceeds the configured budget "
                f"{hbm_budget_bytes / 2**30:.2f} GiB "
                f"(params {params_pd / 2**30:.2f} + opt {opt_pd / 2**30:.2f} "
                f"+ grads {grads_pd / 2**30:.2f} "
                f"+ non-donated {donation_extra_pd / 2**30:.2f} "
                f"+ batch {batch_pd / 2**30:.2f}); shard more axes, donate "
                "state, or use a bigger slice",
                file=source_file))

    return diags, hbm, notes
