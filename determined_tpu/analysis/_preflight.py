"""Preflight orchestration: config -> Report.

Three layers, cheapest first:
  1. config cross-field checks (DTL2xx) — dict only
  2. AST lint over the model-def directory (DTL1xx) — source only
  3. abstract trace of the trial (DTL0xx) — requires importing the trial
     class; degrades to a note (never a crash) when the trial can't be
     loaded, so `det preflight` is useful even on partial checkouts.

Trial discovery: every `*.py` in the context dir is scanned (AST, not
imported) for JaxTrial subclasses; matching modules are imported and the
class instantiated with a TrialContext built from the config's
hyperparameters. A trial whose __init__ needs real data should keep it
lazy (build_training_data) — that is already the platform idiom.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from determined_tpu.analysis import abstract as abstract_mod
from determined_tpu.analysis import astlint, config_rules
from determined_tpu.analysis.diagnostics import Report, filter_suppressed

# Config block recognised by both this analyzer and the native master:
#   preflight:
#     gate: error | warn | off        (default warn: never hard-fail)
#     suppress: [DTL001, ...]
#     hbm_gb_per_device: 16           (enables DTL004)
GATE_MODES = ("error", "warn", "off")


def _preflight_block(config: Dict[str, Any]) -> Dict[str, Any]:
    block = config.get("preflight")
    return block if isinstance(block, dict) else {}


def _hparam_values(hparams: Dict[str, Any]) -> Dict[str, Any]:
    """Collapse hparam specs to representative values for TrialContext.

    Search specs (int/double/log/categorical) take a sample from their
    range — the analyzer needs *a* valid instantiation, not the tuned one;
    shapes and sharding do not depend on where in the range it lands (and
    when they do, e.g. a searched layer width, any sample is as
    representative as any other).
    """
    out: Dict[str, Any] = {}
    for k, v in (hparams or {}).items():
        if isinstance(v, dict) and isinstance(v.get("type"), str):
            t = v["type"]
            if t == "const":
                out[k] = v.get("val")
            elif t in ("int", "double", "log") and "minval" in v:
                out[k] = v["minval"]
            elif t == "categorical" and v.get("vals"):
                out[k] = v["vals"][0]
            else:
                out[k] = v
        elif isinstance(v, dict) and k != "mesh" and v and \
                all(isinstance(sv, dict) for sv in v.values()):
            out[k] = _hparam_values(v)  # nested hparam group
        else:
            out[k] = v
    return out


def find_trial_classes(context_dir: str) -> List[Tuple[str, str]]:
    """[(py_path, class_name)] for JaxTrial subclasses, via AST only."""
    out: List[Tuple[str, str]] = []
    for path in sorted(astlint.iter_py_files([context_dir])):
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for b in node.bases:
                name = b.attr if isinstance(b, ast.Attribute) else getattr(
                    b, "id", "")
                if name == "JaxTrial":
                    out.append((path, node.name))
    return out


def load_trial(
    path: str, class_name: str, hparams: Dict[str, Any], n_devices: int
) -> Any:
    """Import `path` and instantiate `class_name` with a TrialContext."""
    from determined_tpu.train.trial import TrialContext

    mod_name = f"_det_preflight_{os.path.splitext(os.path.basename(path))[0]}"
    spec = importlib.util.spec_from_file_location(mod_name, path)
    assert spec is not None and spec.loader is not None
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, os.path.dirname(os.path.abspath(path)))
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.pop(0)
    cls = getattr(module, class_name)
    ctx = TrialContext(hparams=_hparam_values(hparams), n_devices=n_devices)
    return cls(ctx)


def preflight_trial(
    trial: Any,
    n_devices: int,
    batch: Any = None,
    suppress: Optional[List[str]] = None,
    hbm_budget_bytes: Optional[int] = None,
    source_file: Optional[str] = None,
) -> Report:
    """Run both engines over an in-memory trial instance (test entry point)."""
    report = Report()
    ast_diags = []
    if source_file is None:
        mod = sys.modules.get(type(trial).__module__)
        source_file = getattr(mod, "__file__", None)
    if source_file and os.path.exists(source_file):
        with open(source_file, encoding="utf-8") as f:
            ast_diags = astlint.lint_source(f.read(), filename=source_file)
        report.extend(ast_diags)
    excused = any(d.code == "DTL101" and not d.suppressed for d in ast_diags)
    diags, hbm, notes = abstract_mod.analyze_trial(
        trial, n_devices, batch=batch, hbm_budget_bytes=hbm_budget_bytes,
        source_file=source_file, trace_failure_excused=excused)
    report.extend(diags)
    report.hbm = hbm
    report.notes.extend(notes)
    report.diagnostics = filter_suppressed(report.diagnostics, suppress or [])
    return report


def preflight(
    config: Dict[str, Any],
    context_dir: Optional[str] = None,
    load_trials: bool = True,
) -> Report:
    """Full preflight of an experiment config (+ optional model-def dir)."""
    from determined_tpu import expconf

    config = expconf.shim(config)
    block = _preflight_block(config)
    suppress = [str(c) for c in block.get("suppress", []) or []]
    hbm_budget = None
    if block.get("hbm_gb_per_device"):
        hbm_budget = int(float(block["hbm_gb_per_device"]) * 2**30)

    report = Report()
    report.extend(config_rules.check_config(config))

    slots = (config.get("resources") or {}).get("slots_per_trial", 1)
    n_devices = slots if isinstance(slots, int) and slots > 0 else 1

    if context_dir:
        report.extend(astlint.lint_paths([context_dir]))
        if load_trials:
            classes = find_trial_classes(context_dir)
            if not classes:
                report.notes.append(
                    "no JaxTrial subclass found in the context directory; "
                    "abstract (HBM/sharding) analysis skipped")
            for path, class_name in classes:
                try:
                    trial = load_trial(
                        path, class_name,
                        config.get("hyperparameters") or {}, n_devices)
                except Exception as e:
                    report.notes.append(
                        f"could not load {class_name} from {path}: "
                        f"{type(e).__name__}: {e}; abstract analysis skipped")
                    continue
                excused = any(
                    d.code == "DTL101" and d.file == path and not d.suppressed
                    for d in report.diagnostics)
                diags, hbm, notes = abstract_mod.analyze_trial(
                    trial, n_devices, hbm_budget_bytes=hbm_budget,
                    source_file=path, trace_failure_excused=excused)
                report.extend(diags)
                report.hbm = hbm
                report.notes.extend(notes)
                report.extend(_elastic_hbm_diags(
                    trial, config, n_devices, hbm_budget, path))

    report.diagnostics = filter_suppressed(report.diagnostics, suppress)
    return report


def _elastic_hbm_diags(trial: Any, config: Dict[str, Any], preferred: int,
                       hbm_budget: Optional[int],
                       source_file: Optional[str]) -> List[Any]:
    """DTL204's HBM leg: re-run the abstract-trace engine per candidate
    mesh for every slot count in [min_slots, max_slots] (docs/elasticity.md)
    — a shrink target whose per-device footprint blows the budget would
    OOM exactly when the scheduler tries to save the trial from a drain.
    Requires an armed budget (preflight.hbm_gb_per_device), like DTL004."""
    from determined_tpu.analysis.rules import RULES
    from determined_tpu.parallel.mesh import MeshConfig

    res = config.get("resources") or {}
    elastic = res.get("elastic") if isinstance(res, dict) else None
    if hbm_budget is None or not isinstance(elastic, dict):
        return []
    mn = elastic.get("min_slots", 1)
    mx = elastic.get("max_slots", preferred)
    if not (isinstance(mn, int) and isinstance(mx, int) and 1 <= mn <= mx):
        return []
    try:
        mesh_cfg = trial.mesh_config()
    except Exception:
        mesh_cfg = MeshConfig()
    out = []
    for k in range(mn, mx + 1):
        if k == preferred:
            continue  # the main analysis already covered the preferred size
        if not mesh_cfg.resolvable(k):
            continue  # the config rule reports unresolvable sizes
        diags, _, _ = abstract_mod.analyze_trial(
            trial, k, hbm_budget_bytes=hbm_budget, source_file=source_file)
        for d in diags:
            if d.code == "DTL004" and not d.suppressed:
                out.append(RULES["DTL204"].diag(
                    f"elastic size {k} (of [{mn}, {mx}]): {d.message}",
                    file=source_file))
    return out


def gate_mode(config: Dict[str, Any]) -> str:
    mode = _preflight_block(config).get("gate", "warn")
    return mode if mode in GATE_MODES else "warn"


def should_fail(config: Dict[str, Any], report: Report) -> bool:
    """The master-side gate contract: hard-fail only on error-level rules,
    and only when the config opted in with `preflight: {gate: error}`."""
    return gate_mode(config) == "error" and bool(report.errors)
