"""Static trial preflight analysis — shard/HBM/recompile diagnostics
before any TPU time is spent.

The platform delegates all compute to user code, so a bad trial (state not
donated, an implicitly replicated embedding, a host sync inside the jitted
step) traditionally fails only *after* the scheduler has allocated a pod
slice — the most expensive possible place to discover it.  This package
finds those trials at `experiment create` time, on CPU, in milliseconds:

  - engine 1 (`abstract`): `jax.eval_shape` traces of the trial's train
    state and step under the *declared* mesh — per-device HBM footprint and
    the DTL00x rules — without touching a device.
  - engine 2 (`astlint`): an AST walk of trial/model-def source for host
    syncs, Python RNG / wall-clock reads and shape-dependent branching
    inside traced functions — the DTL1xx rules.
  - config cross-field checks (`config_rules`): the DTL2xx rules, also
    enforced natively by the master at experiment create.

Surfaces: `det preflight <config> [context_dir]`, the master-side create
gate, `python -m determined_tpu.analysis <paths>` (make lint), and pytest
(tests/test_preflight.py).  Every rule is suppressible via the config
(`preflight: {suppress: [DTLnnn]}`) or a `# det: noqa[DTLnnn]` comment.
See docs/preflight.md for the full rule table.
"""

from determined_tpu.analysis.diagnostics import (  # noqa: F401
    Diagnostic,
    Report,
    filter_suppressed,
)
from determined_tpu.analysis.rules import RULES, Rule  # noqa: F401
from determined_tpu.analysis.config_rules import check_config  # noqa: F401

# The engines import jax; load them lazily (PEP 562) so importing
# `determined_tpu.analysis.config_rules` from expconf/CLI stays cheap.
_LAZY = {
    "analyze_trial": ("determined_tpu.analysis.abstract", "analyze_trial"),
    "lint_paths": ("determined_tpu.analysis.astlint", "lint_paths"),
    "lint_source": ("determined_tpu.analysis.astlint", "lint_source"),
    "preflight": ("determined_tpu.analysis._preflight", "preflight"),
    "preflight_trial": ("determined_tpu.analysis._preflight",
                        "preflight_trial"),
    "should_fail": ("determined_tpu.analysis._preflight", "should_fail"),
    "gate_mode": ("determined_tpu.analysis._preflight", "gate_mode"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
