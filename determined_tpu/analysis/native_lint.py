"""Native locking-convention and registry lint (docs/static-analysis.md).

The compile-time half of the thread-safety gate is clang's
``-Wthread-safety`` over the annotations in
``native/common/thread_annotations.h`` (``make -C native tsa``). This
module is the other half: the conventions the annotations cannot express,
and the cross-language registries that must not drift — checked with the
same both-directions contract metric_lint applies to metric names.

Rules (the table lives in docs/static-analysis.md):

  NL001  every ``*_locked`` function declares ``REQUIRES(...)``
  NL002  every field of a Mutex-bearing class in the annotated headers is
         ``GUARDED_BY``, an atomic/const/lock type, or carries an explicit
         ``not-guarded:`` justification
  NL003  ``NO_THREAD_SAFETY_ANALYSIS`` escapes: at most 3 across native/,
         each with an inline ``// tsa:`` justification
  NL004  fault points: C++ ``FAULT_POINT`` sites == the kKnown catalogue,
         and (C++ ∪ Python) emitted points == the rows in docs/chaos.md
  NL005  REST route roots dispatched by the master == the path roots in
         the served OpenAPI document

Run by ``make lint`` via ``python -m determined_tpu.analysis``.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Headers whose Mutex-bearing classes are held to the NL002 field
# discipline. native/common/mutex.h is excluded by construction — it IS
# the capability wrapper.
ANNOTATED_HEADERS = [
    "native/master/master.h",
    "native/master/rm.h",
]

# File-scope globals in these sources follow the same discipline (the
# agent has no header; its shared state is file-scope ``g_*``).
GLOBAL_SOURCES = [
    "native/agent/main.cc",
    "native/common/http.cc",
    "native/common/faultpoint.cc",
]

# Python subsystems that emit fault points, as ``fire("...")`` literals or
# module-level ``FAULT_* = "..."`` constants.
PY_FAULT_SOURCES = [
    "determined_tpu/common/trace.py",
    "determined_tpu/core/_integrity.py",
    "determined_tpu/data/prefetch.py",
    "determined_tpu/serve/scheduler.py",
    "determined_tpu/serve/tracing.py",
    "determined_tpu/train/trainer.py",
]

MAX_TSA_ESCAPES = 3

_LOCKED_DECL_RE = re.compile(r"\b(\w+_locked)\s*\(")
_FAULT_SITE_RE = re.compile(r'FAULT_POINT\("([a-z0-9_.]+)"\)')
_KKNOWN_RE = re.compile(r'^\s*\{"([a-z0-9_.]+)",\s*"(?:master|agent)"',
                        re.MULTILINE)
_PY_FIRE_RE = re.compile(r'\bfire\("([a-z0-9_.]+)"\)')
_PY_CONST_RE = re.compile(r'^FAULT\w*\s*=\s*"([a-z0-9_.]+)"', re.MULTILINE)
_CHAOS_ROW_RE = re.compile(r"^\| `([a-z0-9_.]+)`", re.MULTILINE)
_ROUTE_ROOT_RE = re.compile(r'root == "([\w-]+)"')


def _read(relpath: str, root: str = REPO_ROOT) -> str:
    with open(os.path.join(root, relpath)) as f:
        return f.read()


def _strip_comments(text: str) -> str:
    """// and /* */ comments → spaces (offsets preserved line-wise)."""
    text = re.sub(r"/\*.*?\*/", lambda m: re.sub(r"[^\n]", " ", m.group(0)),
                  text, flags=re.DOTALL)
    return re.sub(r"//[^\n]*", "", text)


def _native_files(root: str) -> List[str]:
    out = []
    for pat in ("native/**/*.h", "native/**/*.cc"):
        for path in sorted(glob.glob(os.path.join(root, pat),
                                     recursive=True)):
            out.append(os.path.relpath(path, root))
    return out


# -- NL001 -----------------------------------------------------------------

def _check_locked_requires(root: str) -> List[str]:
    problems = []
    for rel in _native_files(root):
        if rel.endswith("thread_annotations.h"):
            continue
        raw = _read(rel, root)
        text = _strip_comments(raw)
        for m in _LOCKED_DECL_RE.finditer(text):
            name = m.group(1)
            # Only declarations/definitions, not call sites. Headers hold
            # declarations; in .cc files a definition starts at column 0
            # (possibly Master::-qualified — those carry REQUIRES on the
            # header declaration and are skipped here).
            line_start = text.rfind("\n", 0, m.start()) + 1
            prefix = text[line_start:m.start()]
            if rel.endswith(".cc"):
                if not prefix or prefix[0].isspace():
                    continue  # indented = call site / member expression
                if "::" in prefix:
                    continue  # method definition; header declares REQUIRES
            else:
                # In a header a call would be inside an inline body —
                # require the match to be a declaration: previous
                # non-space char ends a type or access specifier.
                prev = text[:m.start()].rstrip()[-1:]
                if prev and prev not in "&*>;{}:\n" and not (
                        prev.isalnum() or prev == "_"):
                    continue
            stop_semi = text.find(";", m.end())
            stop_brace = text.find("{", m.end())
            stops = [s for s in (stop_semi, stop_brace) if s != -1]
            decl = text[m.start():min(stops)] if stops else text[m.start():]
            if "REQUIRES" not in decl:
                line = text.count("\n", 0, m.start()) + 1
                problems.append(
                    f"{rel}:{line}: NL001 {name} does not declare "
                    "REQUIRES(<mutex>) — the _locked suffix is a checked "
                    "contract, not a naming habit")
    return problems


# -- NL002 -----------------------------------------------------------------

_MEMBER_SKIP_RE = re.compile(
    r"^\s*(friend|using|typedef|static|enum|struct|class|public|private|"
    r"protected|explicit|virtual|template|return|if|for|while|switch|#)\b")
_LOCK_FREE_TYPES = ("std::atomic", "Mutex", "std::condition_variable",
                    "const ")


def _class_scopes(text: str) -> List[Tuple[str, int, int, int]]:
    """(name, body_start, body_end, depth) for each class/struct body."""
    scopes = []
    stack = []  # (name_or_None, open_idx)
    pending = None
    i = 0
    header_re = re.compile(r"\b(?:class|struct)\s+(?:CAPABILITY\([^)]*\)\s*|"
                           r"SCOPED_CAPABILITY\s*)?(\w+)[^;{(]*$")
    while i < len(text):
        c = text[i]
        if c == "{":
            line_start = text.rfind("\n", 0, i) + 1
            head = text[line_start:i].strip()
            m = header_re.search(head)
            stack.append((m.group(1) if m else None, i))
        elif c == "}":
            if stack:
                name, start = stack.pop()
                if name:
                    scopes.append((name, start + 1, i, len(stack)))
        i += 1
        pending = pending  # keep lints quiet
    return scopes


def _check_guarded_fields(root: str) -> List[str]:
    problems = []
    for rel in ANNOTATED_HEADERS:
        if not os.path.exists(os.path.join(root, rel)):
            problems.append(f"{rel}: NL002 annotated header missing (update "
                            "analysis/native_lint.py ANNOTATED_HEADERS)")
            continue
        raw = _read(rel, root)
        text = _strip_comments(raw)
        for name, start, end, _depth in _class_scopes(text):
            if name in ("Mutex", "MutexLock"):
                continue
            stmts = _depth0_statements(text, start, end)
            # Mutex-bearing = declares a det::Mutex member at its own
            # depth (directly or via a pointer) — those classes owe an
            # account of every field. Nested classes are their own scopes.
            if not any(re.search(r"\bMutex\b(?!Lock)", s) for _pos, s
                       in stmts):
                continue
            problems += _check_scope_fields(rel, raw, text, name, stmts)
    return problems


def _depth0_statements(text: str, start: int,
                       end: int) -> List[Tuple[int, str]]:
    """(start_offset, text) of each ';'-terminated statement at the
    scope's own brace depth (nested bodies collapse into their
    statement)."""
    stmts = []
    depth = 0
    stmt_start = start
    for i in range(start, end):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                stmt_start = i + 1
        elif c == ";" and depth == 0:
            seg = text[stmt_start:i]
            lead = len(seg) - len(seg.lstrip())
            stmts.append((stmt_start + lead, seg.strip()))
            stmt_start = i + 1
    return stmts


def _check_scope_fields(rel: str, raw: str, text: str, cls: str,
                        stmts: List[Tuple[int, str]]) -> List[str]:
    problems = []
    raw_lines = raw.splitlines()
    for pos, stmt in stmts:
        if not stmt or _MEMBER_SKIP_RE.match(stmt):
            continue
        # A '(' outside GUARDED_BY/PT_GUARDED_BY = function declaration.
        probe = re.sub(r"(?:PT_)?GUARDED_BY\([^)]*\)", "", stmt)
        probe = re.sub(r"\{[^}]*\}", "", probe)
        if "(" in probe:
            continue
        if "GUARDED_BY" in stmt:
            continue
        if any(t in stmt for t in _LOCK_FREE_TYPES):
            continue
        # Justified? ('not-guarded:' in the member's own comment or the
        # comment block right above it — comments live in `raw`.)
        line = text.count("\n", 0, pos) + 1
        end_line = line + stmt.count("\n") + 1
        ctx = "\n".join(raw_lines[max(0, line - 5):end_line])
        if "not-guarded:" in ctx:
            continue
        member = re.sub(r"=.*", "", stmt).strip().split()[-1]
        problems.append(
            f"{rel}:{line}: NL002 {cls}::{member} is neither GUARDED_BY, "
            "an atomic/const/lock type, nor justified with a "
            "'not-guarded:' comment")
    return problems


def _check_globals(root: str) -> List[str]:
    problems = []
    decl_re = re.compile(r"^[A-Za-z_][\w:<>,&* ]*?\b(g_\w+)\s*(GUARDED_BY"
                         r"\([^)]*\))?\s*(?:\{[^}]*\}|=[^;]*)?;",
                         re.MULTILINE)
    for rel in GLOBAL_SOURCES:
        if not os.path.exists(os.path.join(root, rel)):
            problems.append(f"{rel}: NL002 global source missing (update "
                            "analysis/native_lint.py GLOBAL_SOURCES)")
            continue
        raw = _read(rel, root)
        text = _strip_comments(raw)
        raw_lines = raw.splitlines()
        for m in decl_re.finditer(text):
            stmt = m.group(0)
            if m.group(2) or any(t in stmt for t in _LOCK_FREE_TYPES):
                continue
            line = text.count("\n", 0, m.start()) + 1
            ctx = "\n".join(raw_lines[max(0, line - 4):line + 1])
            if "not-guarded:" in ctx:
                continue
            problems.append(
                f"{rel}:{line}: NL002 global {m.group(1)} is neither "
                "GUARDED_BY, an atomic/const/lock type, nor justified "
                "with a 'not-guarded:' comment")
    return problems


# -- NL003 -----------------------------------------------------------------

def _check_tsa_escapes(root: str) -> Tuple[List[str], int]:
    problems = []
    count = 0
    for rel in _native_files(root):
        if rel.endswith("thread_annotations.h"):
            continue
        raw = _read(rel, root)
        text = _strip_comments(raw)
        raw_lines = raw.splitlines()
        for m in re.finditer(r"\bNO_THREAD_SAFETY_ANALYSIS\b", text):
            count += 1
            line = text.count("\n", 0, m.start()) + 1
            ctx = "\n".join(raw_lines[max(0, line - 3):line + 1])
            if "tsa:" not in ctx:
                problems.append(
                    f"{rel}:{line}: NL003 NO_THREAD_SAFETY_ANALYSIS "
                    "without an inline '// tsa:' justification")
    if count > MAX_TSA_ESCAPES:
        problems.append(
            f"native/: NL003 {count} NO_THREAD_SAFETY_ANALYSIS escapes "
            f"(budget is {MAX_TSA_ESCAPES}) — annotate properly instead")
    return problems, count


# -- NL004 -----------------------------------------------------------------

def _check_fault_registry(root: str) -> List[str]:
    problems = []
    cpp_sites: Set[str] = set()
    for rel in _native_files(root):
        if rel.endswith(".cc"):
            cpp_sites |= set(_FAULT_SITE_RE.findall(_read(rel, root)))
    catalogue_rel = "native/common/faultpoint.cc"
    if not os.path.exists(os.path.join(root, catalogue_rel)):
        return problems + [f"{catalogue_rel}: NL004 fault-point catalogue "
                           "missing"]
    kknown = set(_KKNOWN_RE.findall(_read(catalogue_rel, root)))
    for p in sorted(cpp_sites - kknown):
        problems.append(
            f"native/: NL004 fault point {p!r} fired but missing from the "
            "kKnown catalogue in common/faultpoint.cc")
    for p in sorted(kknown - cpp_sites):
        problems.append(
            f"native/common/faultpoint.cc: NL004 catalogue entry {p!r} has "
            "no FAULT_POINT call site (stale row)")

    py_points: Set[str] = set()
    for rel in PY_FAULT_SOURCES:
        if not os.path.exists(os.path.join(root, rel)):
            problems.append(f"{rel}: NL004 fault source missing (update "
                            "analysis/native_lint.py PY_FAULT_SOURCES)")
            continue
        text = _read(rel, root)
        py_points |= set(_PY_FIRE_RE.findall(text))
        py_points |= set(_PY_CONST_RE.findall(text))

    if not os.path.exists(os.path.join(root, "docs/chaos.md")):
        return problems + ["docs/chaos.md: NL004 fault-point doc missing"]
    documented = set(_CHAOS_ROW_RE.findall(_read("docs/chaos.md", root)))
    emitted = cpp_sites | kknown | py_points
    for p in sorted(emitted - documented):
        problems.append(
            f"docs/chaos.md: NL004 fault point {p!r} emitted but not "
            "documented (add a row to the fault-point table)")
    for p in sorted(documented - emitted):
        problems.append(
            f"docs/chaos.md: NL004 fault point {p!r} documented but "
            "emitted nowhere (stale row)")
    return problems


# -- NL005 -----------------------------------------------------------------

def _check_routes(root: str) -> List[str]:
    problems = []
    for rel in ("native/master/master.cc", "proto/openapi.json"):
        if not os.path.exists(os.path.join(root, rel)):
            return [f"{rel}: NL005 route source missing"]
    dispatched = set(_ROUTE_ROOT_RE.findall(
        _read("native/master/master.cc", root)))
    with open(os.path.join(root, "proto/openapi.json")) as f:
        spec = json.load(f)
    served: Set[str] = set()
    for path in spec.get("paths", {}):
        parts = path.split("/")
        if len(parts) > 3 and parts[1] == "api" and parts[2] == "v1":
            served.add(parts[3])
    for r in sorted(dispatched - served):
        problems.append(
            f"proto/openapi.json: NL005 route root {r!r} dispatched by the "
            "master but absent from the OpenAPI document (add it to "
            "proto/gen_openapi.py ROUTES and regenerate)")
    for r in sorted(served - dispatched):
        problems.append(
            f"native/master/master.cc: NL005 OpenAPI path root {r!r} is "
            "not dispatched by Master::route (stale spec row)")
    return problems


# -- entry -----------------------------------------------------------------

def lint_native(root: str = REPO_ROOT) -> List[str]:
    """Returns violation strings (empty = clean)."""
    problems: List[str] = []
    problems += _check_locked_requires(root)
    problems += _check_guarded_fields(root)
    problems += _check_globals(root)
    escape_problems, _count = _check_tsa_escapes(root)
    problems += escape_problems
    problems += _check_fault_registry(root)
    problems += _check_routes(root)
    return problems


def tsa_escape_count(root: str = REPO_ROOT) -> int:
    return _check_tsa_escapes(root)[1]


def main() -> int:
    problems = lint_native()
    for p in problems:
        print(f"native-lint: {p}")
    print(f"native-lint: {len(problems)} finding(s), "
          f"{tsa_escape_count()}/{MAX_TSA_ESCAPES} "
          "NO_THREAD_SAFETY_ANALYSIS escapes")
    return 1 if problems else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
