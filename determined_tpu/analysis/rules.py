"""The DTL rule registry.

Code ranges by engine:
  DTL0xx — abstract trace (jax.eval_shape over the declared mesh)
  DTL1xx — AST lint of trial / model-def source
  DTL2xx — experiment-config cross-field checks (also enforced natively by
           the master at experiment create; see native/master/preflight.cc)

Levels: "error" rules describe trials that will waste or exhaust TPU HBM /
compile time with certainty; "warning" rules describe likely-but-not-certain
problems. The master-side gate hard-fails only error-level rules, and only
when the experiment config opts in (`preflight: {gate: error}`).

Every rule is suppressible:
  - per line (AST rules):   `# det: noqa[DTL101]`  or  `# det: noqa`
  - per experiment config:  `preflight: {suppress: [DTL001, ...]}`
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from determined_tpu.analysis.diagnostics import Diagnostic


@dataclasses.dataclass(frozen=True)
class Rule:
    code: str
    name: str
    level: str  # default level: "error" | "warning"
    engine: str  # "abstract" | "ast" | "config"
    summary: str

    def diag(self, message: str, **kw) -> Diagnostic:
        return Diagnostic(code=self.code, message=message, level=self.level,
                          engine=self.engine, **kw)


_ALL = [
    # -- engine 1: abstract trace ---------------------------------------
    Rule("DTL001", "state-not-donated", "error", "abstract",
         "train state is not donated to the jitted step; params + optimizer "
         "state are held twice in HBM (old state alive while the new one is "
         "computed) — ~2x the steady-state footprint"),
    Rule("DTL002", "implicit-replication", "warning", "abstract",
         "a large parameter leaf has no sharded dimension under the declared "
         "mesh and is fully replicated on every device"),
    Rule("DTL003", "batch-mesh-mismatch", "error", "abstract",
         "the global batch produced by the data loader is not divisible by "
         "the mesh's batch (data x fsdp) axes; GSPMD would pad or fail at "
         "dispatch"),
    Rule("DTL004", "hbm-over-budget", "error", "abstract",
         "the estimated per-device HBM lower bound (params + optimizer state "
         "+ grads + batch) exceeds the configured per-device HBM budget"),
    Rule("DTL005", "abstract-trace-failed", "warning", "abstract",
         "the train step could not be traced abstractly (jax.eval_shape "
         "raised); HBM and sharding analysis is incomplete"),
    # -- engine 2: AST lint ---------------------------------------------
    Rule("DTL101", "host-sync-in-step", "error", "ast",
         "host synchronization inside a traced function (jax.device_get / "
         ".item() / .block_until_ready() / np.asarray on a traced value): "
         "stalls the device pipeline every step, or fails to trace at all"),
    Rule("DTL102", "python-rng-in-step", "warning", "ast",
         "Python / numpy RNG inside a traced function: the value is baked in "
         "at trace time and identical for every step — use jax.random with a "
         "threaded key instead"),
    Rule("DTL103", "wall-clock-in-step", "warning", "ast",
         "wall-clock read inside a traced function: the value is baked in at "
         "trace time, not read per step"),
    Rule("DTL104", "shape-branch-in-step", "warning", "ast",
         "Python branching on shapes inside a traced function: each distinct "
         "shape compiles a new executable (recompile hazard on variable "
         "batches/sequence lengths)"),
    Rule("DTL105", "device-transfer-in-data-loader", "warning", "ast",
         "build_training_data / build_validation_data transfers batches to "
         "device itself (jax.device_put / jnp arrays): the async input "
         "pipeline already shards and device_puts batches with the mesh "
         "batch sharding, so the loader's transfer is paid twice — yield "
         "host (numpy) batches, or disable prefetch for this trial"),
    Rule("DTL106", "thread-stop-shadowing", "error", "ast",
         "a threading.Thread subclass defines an attribute, Event or method "
         "named `_stop`: CPython's Thread uses self._stop() internally "
         "(join / _wait_for_tstate_lock call it on thread exit), so "
         "shadowing it with an Event raises `TypeError: 'Event' object is "
         "not callable` when the thread finishes — name the flag "
         "`_stop_evt` (the convention used by core/_profiler.py and "
         "core/_preempt.py) instead"),
    Rule("DTL107", "hand-rolled-attention-in-trial", "warning", "ast",
         "trial code computes attention by hand (jax.nn.softmax / a manual "
         "QK^T-softmax-V chain) inside a traced trial method: the "
         "`optimizations.attention_impl` config knob (pallas flash "
         "attention, bf16 path — docs/training-perf.md) cannot reach a "
         "hand-rolled softmax, so platform-level attention A/Bs silently "
         "measure nothing — route attention through the model library "
         "(e.g. ops/flash_attention.flash_attention) or suppress if the "
         "softmax is not attention"),
    # -- config cross-field checks --------------------------------------
    Rule("DTL201", "config-batch-mesh-mismatch", "error", "config",
         "hyperparameters.global_batch_size is not divisible by the mesh's "
         "batch (data x fsdp) axes resolved against resources.slots_per_trial"),
    Rule("DTL202", "searcher-budget-rungs", "error", "config",
         "searcher.max_length cannot populate the configured ASHA rungs "
         "(max_length < divisor^(num_rungs-1)); top rungs would be "
         "unreachable and the search degenerates"),
    Rule("DTL203", "restarts-without-checkpoints", "warning", "config",
         "min_checkpoint_period is explicitly 0 (op-boundary checkpoints "
         "only) while max_restarts > 0: a mid-op failure restarts from the "
         "previous op boundary or from scratch — restarts are configured "
         "but there is nothing recent to restart from"),
    Rule("DTL204", "elastic-size-infeasible", "error", "config",
         "an elastic config (resources.elastic) must be runnable at EVERY "
         "slot count in [min_slots, max_slots]: the mesh must resolve, "
         "global_batch_size must divide over the batch axes, and the "
         "per-device HBM footprint must fit the budget at each size — a "
         "size that fails only surfaces mid-drain, exactly when the "
         "scheduler tries to shrink onto surviving capacity"),
    Rule("DTL205", "unbucketed-shape-sweep", "warning", "config",
         "the searcher sweeps shape-affecting hyperparameters (e.g. raw "
         "global_batch_size sampling) into more distinct executables than "
         "compile.max_executables: every distinct shape pays a full XLA "
         "compile and defeats executable sharing across the sweep — bucket "
         "batch sizes (compile.bucket_batch_sizes), sample fewer distinct "
         "shape values, or raise compile.max_executables if the compile "
         "cost is intended"),
    Rule("DTL206", "serving-kv-geometry", "error", "config",
         "a serving config's paged KV geometry is unusable: kv_block_size "
         "must divide max_seq_len (the block tables tile max_seq_len "
         "exactly), and an explicit kv_num_blocks must give the pool room "
         "for at least one max_seq_len sequence — otherwise the replica "
         "fails at engine startup (or requests can never be admitted) "
         "instead of at config time"),
    Rule("DTL207", "serving-capacity-knobs", "error", "config",
         "a deployment's capacity-loop knobs are unsatisfiable "
         "(docs/cluster-ops.md 'Capacity loop'): serving.replicas.min "
         "must be >= 0 (0 = scale-to-zero) and <= max, "
         "on_demand_floor must fit within [0, max] (a floor above max "
         "can never be met), and cold_start_budget_s must be a positive "
         "number — it bounds how long the router holds a request while a "
         "scale-from-zero replica restores"),
    Rule("DTL208", "serving-canary-fraction", "error", "config",
         "a config-declared canary split (serving.canary) must carry a "
         "traffic fraction strictly inside (0, 1): 0 routes nothing to "
         "the canary (it burns a replica for no signal) and 1 is a full "
         "rollout that should be a rolling update instead — the router's "
         "deterministic debt split is only meaningful for a real "
         "fraction (docs/serving.md 'Model lifecycle')"),
]

RULES: Dict[str, Rule] = {r.code: r for r in _ALL}


def get(code: str) -> Rule:
    return RULES[code]
