"""Diagnostic records and report formatting shared by both engines."""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence

_CODE_RE = re.compile(r"^DTL\d{3}$")


@dataclasses.dataclass
class Diagnostic:
    """One finding. `level` is the rule's level unless overridden."""

    code: str
    message: str
    level: str = "warning"  # "error" | "warning"
    file: Optional[str] = None
    line: Optional[int] = None
    engine: str = ""  # "abstract" | "ast" | "config"
    suppressed: bool = False
    suppressed_by: Optional[str] = None  # "noqa" | "config"

    def to_dict(self) -> Dict[str, Any]:
        d = {"code": self.code, "level": self.level, "message": self.message,
             "engine": self.engine}
        if self.file is not None:
            d["file"] = self.file
        if self.line is not None:
            d["line"] = self.line
        if self.suppressed:
            d["suppressed"] = True
            d["suppressed_by"] = self.suppressed_by
        return d

    def location(self) -> str:
        if self.file is None:
            return ""
        return f"{self.file}:{self.line}" if self.line else self.file


def filter_suppressed(
    diagnostics: Iterable[Diagnostic], suppress: Sequence[str] = ()
) -> List[Diagnostic]:
    """Mark config-suppressed codes; returns the full (annotated) list."""
    out = []
    codes = {c for c in suppress if _CODE_RE.match(str(c))}
    for d in diagnostics:
        if not d.suppressed and d.code in codes:
            d = dataclasses.replace(d, suppressed=True, suppressed_by="config")
        out.append(d)
    return out


@dataclasses.dataclass
class Report:
    """A full preflight run: diagnostics + the HBM footprint breakdown."""

    diagnostics: List[Diagnostic] = dataclasses.field(default_factory=list)
    hbm: Dict[str, Any] = dataclasses.field(default_factory=dict)
    notes: List[str] = dataclasses.field(default_factory=list)

    @property
    def active(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.suppressed]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.active if d.level == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.active if d.level == "warning"]

    def codes(self) -> List[str]:
        return sorted({d.code for d in self.active})

    def extend(self, diagnostics: Iterable[Diagnostic]) -> None:
        self.diagnostics.extend(diagnostics)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "summary": {
                "errors": len(self.errors),
                "warnings": len(self.warnings),
                "suppressed": sum(1 for d in self.diagnostics if d.suppressed),
                "codes": self.codes(),
            },
            "hbm": self.hbm,
            "notes": self.notes,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def to_text(self) -> str:
        lines: List[str] = []
        for d in sorted(self.diagnostics,
                        key=lambda d: (d.file or "", d.line or 0, d.code)):
            loc = d.location()
            prefix = f"{loc}: " if loc else ""
            tag = f"{d.level} {d.code}"
            if d.suppressed:
                tag += f" (suppressed: {d.suppressed_by})"
            lines.append(f"{prefix}{tag}: {d.message}")
        if self.hbm:
            lines.append("")
            lines.append("per-device HBM footprint (estimated lower bound):")
            for key in ("params_bytes", "opt_state_bytes", "grads_bytes",
                        "donation_extra_bytes", "batch_bytes",
                        "activations_upper_bound_bytes", "total_bytes"):
                if key in self.hbm:
                    lines.append(f"  {key:30s} {_human(self.hbm[key])}")
            if "budget_bytes" in self.hbm:
                lines.append(f"  {'budget_bytes':30s} {_human(self.hbm['budget_bytes'])}")
        for n in self.notes:
            lines.append(f"note: {n}")
        ne, nw = len(self.errors), len(self.warnings)
        lines.append("")
        if ne or nw:
            lines.append(f"preflight: {ne} error(s), {nw} warning(s)")
        else:
            lines.append("preflight: clean")
        return "\n".join(lines)


def _human(n: Any) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return str(n)
