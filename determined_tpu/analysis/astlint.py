"""Engine 2 — AST lint of trial / model-def source.

Finds host-side constructs inside *traced* functions: code that runs under
`jax.jit`/`eval_shape` tracing, where a host sync stalls the device pipeline
every step, Python RNG / wall-clock values get baked in at trace time, and
shape-dependent branching forces a recompile per distinct shape.

What counts as traced (the roots):
  - methods named loss / loss_pipelined / evaluate / evaluate_pipelined /
    init_params on classes whose bases mention JaxTrial
  - functions decorated with (or wrapped by a call to) jit / jax.jit,
    including functools.partial(jax.jit, ...)
  - module-level functions named loss_fn* / apply* (the pure-model idiom
    used by determined_tpu.models)
plus the same-module call-graph closure of those roots: a helper called
from a traced function is linted as traced.

Torch / Keras / DeepSpeed trials are never traced by JAX, so their
`.item()` calls are fine and their classes are not roots.

Suppression: a trailing `# det: noqa[DTL101]` (or bare `# det: noqa`)
comment suppresses findings on that line; suppressed findings are still
reported, marked suppressed, so `--json` consumers can audit them.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from determined_tpu.analysis.diagnostics import Diagnostic
from determined_tpu.analysis.rules import RULES

TRACED_METHODS = {
    "loss", "loss_pipelined", "evaluate", "evaluate_pipelined", "init_params",
}
# Data-loader roots (DTL105): not traced — linted for the opposite hazard,
# host code that transfers to device itself (double-transfer with the async
# input pipeline, which owns the device_put).
DATA_LOADER_METHODS = {"build_training_data", "build_validation_data"}
TRACED_BASES = {"JaxTrial"}
TRACED_NAME_PREFIXES = ("loss_fn", "apply")
JIT_NAMES = {"jit", "pjit"}

_NOQA_RE = re.compile(
    r"#\s*det:\s*noqa(?:\[(?P<codes>[A-Z0-9,\s]+)\])?", re.IGNORECASE)

# Host-sync callees (DTL101).
_HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_NP_MODULES = {"np", "numpy", "onp"}

# Python RNG callees (DTL102): stdlib `random.` and `np.random.`.
_PY_RNG_FUNCS = {
    "random", "randint", "uniform", "randrange", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate", "random_sample",
}
_NP_RNG_FUNCS = _PY_RNG_FUNCS | {"randn", "rand", "default_rng", "normal",
                                 "integers", "permutation"}

# Wall-clock callees (DTL103).
_CLOCK_FUNCS = {"time", "perf_counter", "monotonic", "process_time", "clock"}


def parse_noqa(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> None (suppress all) | set of codes."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _NOQA_RE.search(line)
        if not m:
            continue
        codes = m.group("codes")
        if codes is None:
            out[i] = None
        else:
            out[i] = {c.strip().upper() for c in codes.split(",") if c.strip()}
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    """Attribute/Name chain -> 'a.b.c' (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """True for `jit`, `jax.jit`, `partial(jax.jit, ...)` expressions."""
    d = _dotted(node)
    if d is not None and (d in JIT_NAMES or d.split(".")[-1] in JIT_NAMES):
        return True
    if isinstance(node, ast.Call):
        f = _dotted(node.func)
        if f is not None and f.split(".")[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        # jax.jit(fn, ...) used as a decorator factory
        return _is_jit_expr(node.func)
    return False


class _ModuleIndex(ast.NodeVisitor):
    """Collect functions, methods, traced roots and a same-module call graph."""

    def __init__(self):
        self.functions: Dict[str, ast.AST] = {}  # qualname -> FunctionDef
        self.roots: Set[str] = set()
        # Trial-method roots only (JaxTrial loss/evaluate/...): the subset
        # DTL107 scopes to — the platform's own model library (module-level
        # loss_fn*/apply* roots) legitimately implements softmax.
        self.trial_roots: Set[str] = set()
        self.data_roots: Set[str] = set()  # build_*_data methods (DTL105)
        self.calls: Dict[str, Set[str]] = {}  # qualname -> called qualnames
        self._class_stack: List[Tuple[str, bool]] = []  # (name, is_jax_trial)

    # -- classes --------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        is_jax = any(
            (_dotted(b) or "").split(".")[-1] in TRACED_BASES
            for b in node.bases
        )
        # Subclass-of-subclass within the same module counts too.
        if not is_jax:
            for b in node.bases:
                base = (_dotted(b) or "").split(".")[-1]
                if any(c == base and j for c, j in self._class_stack):
                    is_jax = True
        self._class_stack.append((node.name, is_jax))
        self.generic_visit(node)
        self._class_stack.pop()

    # -- functions ------------------------------------------------------
    def _qual(self, name: str) -> str:
        if self._class_stack:
            return f"{self._class_stack[-1][0]}.{name}"
        return name

    def _handle_function(self, node) -> None:
        qual = self._qual(node.name)
        self.functions[qual] = node
        in_jax_class = bool(self._class_stack) and self._class_stack[-1][1]
        if in_jax_class and node.name in TRACED_METHODS:
            self.roots.add(qual)
            self.trial_roots.add(qual)
        if in_jax_class and node.name in DATA_LOADER_METHODS:
            self.data_roots.add(qual)
        if not self._class_stack and node.name.startswith(TRACED_NAME_PREFIXES):
            self.roots.add(qual)
        if any(_is_jit_expr(d) for d in node.decorator_list):
            self.roots.add(qual)
        self.calls[qual] = self._collect_calls(node)
        # Do NOT generic_visit: nested defs belong to this function's body
        # and are linted as part of it — EXCEPT the factory idiom
        # `def make_x(): def step(...): ...; return jax.jit(step)`, where
        # the nested def is the traced root and the enclosing factory runs
        # on host. Register jit-wrapped nested defs as their own roots.
        jit_wrapped: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and _is_jit_expr(n.func) and n.args:
                d = _dotted(n.args[0])
                if d is not None and "." not in d:
                    jit_wrapped.add(d)
        if jit_wrapped:
            for n in ast.walk(node):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n is not node and n.name in jit_wrapped:
                    nested_qual = f"{qual}.<locals>.{n.name}"
                    self.functions[nested_qual] = n
                    self.roots.add(nested_qual)
                    self.calls[nested_qual] = self._collect_calls(n)

    visit_FunctionDef = _handle_function
    visit_AsyncFunctionDef = _handle_function

    def _collect_calls(self, node) -> Set[str]:
        cls = self._class_stack[-1][0] if self._class_stack else None
        out: Set[str] = set()
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            if d is None:
                continue
            if d.startswith("self.") and cls is not None:
                out.add(f"{cls}.{d[5:]}")
            elif "." not in d:
                out.add(d)
            # `jax.jit(fn)` anywhere marks fn as a root.
            if _is_jit_expr(n.func):
                for a in n.args[:1]:
                    ad = _dotted(a)
                    if ad is not None:
                        out.add(ad)  # treated as called-from-traced below
        return out

    # module-level `g = jax.jit(f)` marks f as a root
    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call) and _is_jit_expr(node.value.func):
            for a in node.value.args[:1]:
                d = _dotted(a)
                if d is not None:
                    self.roots.add(d)
        self.generic_visit(node)


def _traced_closure(index: _ModuleIndex,
                    roots: Optional[Set[str]] = None) -> Set[str]:
    seen: Set[str] = set()
    frontier = [r for r in (index.roots if roots is None else roots)
                if r in index.functions]
    while frontier:
        fn = frontier.pop()
        if fn in seen:
            continue
        seen.add(fn)
        for callee in index.calls.get(fn, ()):
            if callee in index.functions and callee not in seen:
                frontier.append(callee)
            # `Class.method` calls recorded as bare names can't collide with
            # module functions here; unknown callees are simply skipped.
    return seen


class _RuleWalker(ast.NodeVisitor):
    def __init__(self, filename: str, func_qual: str):
        self.filename = filename
        self.func_qual = func_qual
        self.findings: List[Tuple[str, int, str]] = []  # (code, line, msg)

    def _add(self, code: str, node: ast.AST, msg: str) -> None:
        self.findings.append((code, getattr(node, "lineno", 0), msg))

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        last = d.split(".")[-1] if d else None

        # DTL101 — host sync.
        if isinstance(node.func, ast.Attribute) and not node.args and \
                node.func.attr in _HOST_SYNC_METHODS:
            self._add("DTL101", node,
                      f".{node.func.attr}() inside traced "
                      f"'{self.func_qual}' forces a device->host sync "
                      "(or fails to trace); compute on device and fetch at "
                      "report boundaries")
        elif d is not None and last == "device_get":
            self._add("DTL101", node,
                      f"jax.device_get inside traced '{self.func_qual}' "
                      "forces a device->host sync; fetch at report "
                      "boundaries instead")
        elif d is not None and d.split(".")[0] in _NP_MODULES and \
                last in ("asarray", "array"):
            if node.args and not isinstance(
                    node.args[0], (ast.Constant, ast.List, ast.Tuple)):
                self._add("DTL101", node,
                          f"{d}() on a traced value inside '{self.func_qual}' "
                          "pulls it to the host (TracerArrayConversionError "
                          "under jit); use jnp instead")

        # DTL102 — Python RNG.
        if d is not None and "." in d:
            head, tail = d.split(".", 1)
            if head == "random" and tail in _PY_RNG_FUNCS:
                self._add("DTL102", node,
                          f"random.{tail}() inside traced '{self.func_qual}' "
                          "is evaluated once at trace time; use jax.random "
                          "with a threaded key")
            elif head in _NP_MODULES and tail.startswith("random.") and \
                    tail.split(".")[-1] in _NP_RNG_FUNCS:
                self._add("DTL102", node,
                          f"{d}() inside traced '{self.func_qual}' is "
                          "evaluated once at trace time; use jax.random "
                          "with a threaded key")

        # DTL103 — wall clock.
        if d in {f"time.{f}" for f in _CLOCK_FUNCS} or \
                d in ("datetime.now", "datetime.datetime.now",
                      "datetime.utcnow", "datetime.datetime.utcnow"):
            self._add("DTL103", node,
                      f"{d}() inside traced '{self.func_qual}' is read once "
                      "at trace time, not per step")

        self.generic_visit(node)

    def _shape_dependent(self, test: ast.AST) -> Optional[str]:
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim"):
                return f".{n.attr}"
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d == "len":
                    return "len()"
                if d == "getattr" and len(n.args) >= 2 and isinstance(
                        n.args[1], ast.Constant) and n.args[1].value in (
                            "shape", "ndim"):
                    return f"getattr(..., '{n.args[1].value}')"
        return None

    def _check_branch(self, node) -> None:
        why = self._shape_dependent(node.test)
        if why is not None:
            kind = "while" if isinstance(node, ast.While) else "if"
            self._add("DTL104", node,
                      f"`{kind}` on {why} inside traced '{self.func_qual}': "
                      "each distinct shape compiles a separate executable "
                      "(recompile hazard); keep shapes static or use "
                      "jax.lax.cond/select")
        self.generic_visit(node)

    visit_If = _check_branch
    visit_While = _check_branch

    def visit_IfExp(self, node: ast.IfExp) -> None:
        why = self._shape_dependent(node.test)
        if why is not None:
            self._add("DTL104", node,
                      f"conditional expression on {why} inside traced "
                      f"'{self.func_qual}' (recompile hazard)")
        self.generic_visit(node)


def _thread_stop_findings(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """DTL106 — `_stop` shadowing on threading.Thread subclasses.

    CPython's Thread keeps a private `_stop()` method that `join()` /
    `_wait_for_tstate_lock()` call when the thread finishes.  A subclass
    that rebinds `_stop` to an Event (the classic pre-3.x stop-flag idiom)
    crashes with `TypeError: 'Event' object is not callable` at thread
    exit; rebinding it to a method silently skips Thread's own state
    bookkeeping.  Same-module subclass-of-subclass counts too.
    """
    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    thread_classes: Set[str] = set()
    changed = True
    while changed:  # fixed point over same-module subclassing
        changed = False
        for cls in classes:
            if cls.name in thread_classes:
                continue
            for b in cls.bases:
                base = (_dotted(b) or "").split(".")[-1]
                if base == "Thread" or base in thread_classes:
                    thread_classes.add(cls.name)
                    changed = True
                    break

    findings: List[Tuple[str, int, str]] = []

    def _flag(node: ast.AST, cls: ast.ClassDef, what: str) -> None:
        findings.append((
            "DTL106", getattr(node, "lineno", 0),
            f"Thread subclass '{cls.name}' defines {what} named '_stop', "
            "shadowing threading.Thread._stop() (called by join() on "
            "thread exit); rename it to '_stop_evt'"))

    for cls in classes:
        if cls.name not in thread_classes:
            continue
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name == "_stop":
                    _flag(stmt, cls, "a method")
                    continue
                # self._stop = ... inside any method body.
                for n in ast.walk(stmt):
                    targets: List[ast.AST] = []
                    if isinstance(n, ast.Assign):
                        targets = list(n.targets)
                    elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
                        targets = [n.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr == "_stop" and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            _flag(n, cls, "an instance attribute")
            elif isinstance(stmt, ast.Assign):
                if any(isinstance(t, ast.Name) and t.id == "_stop"
                       for t in stmt.targets):
                    _flag(stmt, cls, "a class attribute")
            elif isinstance(stmt, ast.AnnAssign):
                if isinstance(stmt.target, ast.Name) and \
                        stmt.target.id == "_stop":
                    _flag(stmt, cls, "a class attribute")
    return findings


_JNP_HEADS = {"jnp", "jax.numpy"}

# DTL107 — softmax callees that mark a hand-rolled attention path. Scoped to
# the *trial-method* closure only (index.trial_roots): the platform's own
# model library (module-level loss_fn*/apply* roots, ops/flash_attention.py's
# reference path) legitimately implements softmax. log_softmax is NOT
# flagged — it is the cross-entropy idiom, not attention.
_SOFTMAX_HEADS = {"jax.nn", "nn", "jnn", "jax.scipy.special", "jsp.special"}


class _AttnWalker(ast.NodeVisitor):
    """DTL107 — hand-rolled attention softmax inside traced trial code."""

    def __init__(self, func_qual: str):
        self.func_qual = func_qual
        self.findings: List[Tuple[str, int, str]] = []

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None and d.split(".")[-1] == "softmax":
            head = d.rsplit(".", 1)[0] if "." in d else ""
            if head in _SOFTMAX_HEADS or d == "softmax":
                self.findings.append((
                    "DTL107", getattr(node, "lineno", 0),
                    f"{d}() inside traced trial code '{self.func_qual}': a "
                    "hand-rolled attention softmax bypasses "
                    "`optimizations.attention_impl` (pallas flash attention, "
                    "bf16 path — docs/training-perf.md), so platform "
                    "attention A/Bs never reach this trial — route attention "
                    "through the model library "
                    "(ops/flash_attention.flash_attention) or suppress if "
                    "this softmax is not attention"))
        self.generic_visit(node)


class _DataLoaderWalker(ast.NodeVisitor):
    """DTL105 — device transfer inside build_training/validation_data."""

    def __init__(self, func_qual: str):
        self.func_qual = func_qual
        self.findings: List[Tuple[str, int, str]] = []

    def _add(self, node: ast.AST, msg: str) -> None:
        self.findings.append(("DTL105", getattr(node, "lineno", 0), msg))

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None and d.split(".")[-1] == "device_put":
            self._add(node,
                      f"jax.device_put inside '{self.func_qual}': the async "
                      "input pipeline already device_puts batches with the "
                      "mesh batch sharding — this transfer is paid twice; "
                      "yield host (numpy) batches instead")
        self.generic_visit(node)

    def _check_emitted(self, node, value: Optional[ast.AST]) -> None:
        if not isinstance(value, ast.Call):
            return
        d = _dotted(value.func)
        if d is None:
            return
        head = d.rsplit(".", 1)[0] if "." in d else ""
        if head in _JNP_HEADS or d.startswith("jax.numpy."):
            self._add(node,
                      f"'{self.func_qual}' yields/returns a {d}(...) device "
                      "array: the prefetch pipeline re-transfers it with the "
                      "batch sharding (double transfer); build batches with "
                      "numpy and let the pipeline own the device_put")

    def visit_Yield(self, node: ast.Yield) -> None:
        self._check_emitted(node, node.value)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        self._check_emitted(node, node.value)
        self.generic_visit(node)


def lint_source(
    source: str, filename: str = "<string>"
) -> List[Diagnostic]:
    """Lint one module's source; returns diagnostics (suppressed included)."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [Diagnostic(code="DTL101", level="error", engine="ast",
                           message=f"source does not parse: {e}",
                           file=filename, line=e.lineno or 0)]
    noqa = parse_noqa(source)
    index = _ModuleIndex()
    index.visit(tree)
    traced = _traced_closure(index)

    diags: List[Diagnostic] = []

    def _emit(findings) -> None:
        for code, line, msg in findings:
            rule = RULES[code]
            d = rule.diag(msg, file=filename, line=line)
            codes = noqa.get(line, "absent")
            if codes is None or (codes != "absent" and code in codes):
                d.suppressed = True
                d.suppressed_by = "noqa"
            diags.append(d)

    for qual in sorted(traced):
        walker = _RuleWalker(filename, qual)
        node = index.functions[qual]
        # Visit the body only: decorators/defaults run at def time, on host.
        for stmt in node.body:
            walker.visit(stmt)
        _emit(walker.findings)
    # DTL107 runs over the trial-method closure only (see _SOFTMAX_HEADS).
    for qual in sorted(_traced_closure(index, index.trial_roots)):
        attn_walker = _AttnWalker(qual)
        for stmt in index.functions[qual].body:
            attn_walker.visit(stmt)
        _emit(attn_walker.findings)
    for qual in sorted(index.data_roots):
        dl_walker = _DataLoaderWalker(qual)
        for stmt in index.functions[qual].body:
            dl_walker.visit(stmt)
        _emit(dl_walker.findings)
    # DTL106 applies to every Thread subclass in the module, traced or not.
    _emit(_thread_stop_findings(tree))
    return diags


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if not d.startswith(".") and d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths: Sequence[str]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        diags.extend(lint_source(source, filename=path))
    return diags
