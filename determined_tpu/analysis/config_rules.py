"""Config cross-field checks (DTL2xx).

These run over the experiment-config dict alone — no trial code needed —
which is why the native master re-implements exactly this set in
native/master/preflight.cc and gates experiment creation on it. Keep the
two in lockstep: every rule added here must be added there (and to
docs/preflight.md).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

from determined_tpu.analysis.diagnostics import Diagnostic
from determined_tpu.analysis.rules import RULES
from determined_tpu.parallel.mesh import AXIS_ORDER

# Axes the batch shards over (LogicalRules DEFAULT_RULES "batch" entry).
BATCH_AXES = ("data", "fsdp")


def _length_batches(v: Any) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, dict):
        for unit in ("batches", "records", "epochs"):
            if unit in v:
                return int(v[unit])
    return 0


def resolve_batch_axes_product(config: Dict[str, Any],
                               slots: Any = None) -> int:
    """data*fsdp resolved against slots_per_trial, mirroring
    MeshConfig.resolve (omitted `data` = -1 absorbs remaining chips).
    Returns 0 when the mesh is unresolvable (other validation reports it).
    `slots` overrides resources.slots_per_trial — the DTL204 elastic check
    re-resolves the same mesh at every candidate size.
    """
    hp = config.get("hyperparameters") or {}
    mesh = hp.get("mesh") or {}
    if not isinstance(mesh, dict):
        return 0
    res = config.get("resources") or {}
    if slots is None:
        slots = res.get("slots_per_trial", 1)
    if not isinstance(slots, int) or slots <= 0:
        return 0
    sizes = {a: 1 for a in AXIS_ORDER}
    unknown = []
    for a, v in mesh.items():
        if a not in sizes or isinstance(v, bool) or not isinstance(v, int):
            return 0
        if v == -1:
            unknown.append(a)
        elif v > 0:
            sizes[a] = v
        else:
            return 0
    if "data" not in mesh:
        unknown.append("data")
    if len(unknown) > 1:
        return 0
    fixed = math.prod(sizes[a] for a in AXIS_ORDER if a not in unknown)
    if unknown:
        if slots % fixed != 0:
            return 0
        sizes[unknown[0]] = slots // fixed
    elif fixed != slots:
        return 0
    return sizes["data"] * sizes["fsdp"]


def check_config(config: Dict[str, Any]) -> List[Diagnostic]:
    """DTL201 + DTL202 over a (shimmed) experiment config."""
    diags: List[Diagnostic] = []
    if not isinstance(config, dict):
        return diags

    # DTL201 — global_batch_size vs mesh batch axes.
    hp = config.get("hyperparameters") or {}
    gbs = hp.get("global_batch_size") if isinstance(hp, dict) else None
    if isinstance(gbs, dict):  # hparam spec {type: const, val: N}
        gbs = gbs.get("val") if gbs.get("type") == "const" else None
    if isinstance(gbs, int) and gbs > 0:
        bprod = resolve_batch_axes_product(config)
        if bprod > 1 and gbs % bprod != 0:
            diags.append(RULES["DTL201"].diag(
                f"hyperparameters.global_batch_size={gbs} is not divisible "
                f"by the mesh batch axes data x fsdp = {bprod} (resolved "
                f"against resources.slots_per_trial="
                f"{(config.get('resources') or {}).get('slots_per_trial', 1)})"))

    # DTL202 — ASHA budget vs rungs.
    searcher = config.get("searcher")
    if isinstance(searcher, dict) and searcher.get("name") in (
            "async_halving", "sync_halving"):
        max_length = _length_batches(searcher.get("max_length"))
        num_rungs = searcher.get("num_rungs") or 0
        divisor = searcher.get("divisor") or 4
        if max_length > 0 and isinstance(num_rungs, int) and num_rungs > 1 \
                and isinstance(divisor, (int, float)) and divisor > 1:
            bottom = max_length / (divisor ** (num_rungs - 1))
            if bottom < 1:
                diags.append(RULES["DTL202"].diag(
                    f"searcher.max_length={max_length} < divisor^(num_rungs-1)"
                    f"={int(divisor)}^{num_rungs - 1}="
                    f"{int(divisor ** (num_rungs - 1))}: the bottom rung "
                    "would train for zero batches and the top rungs are "
                    "unreachable; lower num_rungs or raise max_length"))

    # DTL204 — elastic configs must be runnable at EVERY size in
    # [min_slots, max_slots]: the scheduler may re-mesh the trial to any
    # of them on a drain or a scale-up (docs/elasticity.md). Mesh
    # resolvability + batch divisibility here; the HBM-per-size leg runs
    # in preflight() with the abstract-trace engine per candidate mesh.
    res = config.get("resources") or {}
    elastic = res.get("elastic") if isinstance(res, dict) else None
    if isinstance(elastic, dict):
        spt = res.get("slots_per_trial", 1)
        mn = elastic.get("min_slots", 1)
        mx = elastic.get("max_slots", spt if isinstance(spt, int) else 0)
        if isinstance(mn, int) and isinstance(mx, int) and 1 <= mn <= mx:
            gbs_val = gbs if isinstance(gbs, int) and gbs > 0 else None
            for k in range(mn, mx + 1):
                bprod = resolve_batch_axes_product(config, slots=k)
                if bprod == 0:
                    diags.append(RULES["DTL204"].diag(
                        f"elastic size {k} (of [{mn}, {mx}]): "
                        "hyperparameters.mesh does not resolve at this slot "
                        "count — the fixed axes product must divide every "
                        "size the scheduler may shrink/grow the trial to"))
                elif gbs_val is not None and gbs_val % bprod != 0:
                    diags.append(RULES["DTL204"].diag(
                        f"elastic size {k} (of [{mn}, {mx}]): "
                        f"hyperparameters.global_batch_size={gbs_val} is not "
                        f"divisible by the mesh batch axes data x fsdp = "
                        f"{bprod} at this slot count"))

    # DTL203 — restarts configured but nothing to restart from. Only an
    # EXPLICIT min_checkpoint_period: 0 fires (key present): the default is
    # also 0 batches and flagging every config would be pure noise.
    if "min_checkpoint_period" in config:
        mcp = _length_batches(config.get("min_checkpoint_period"))
        mr = config.get("max_restarts", 5)
        if mcp == 0 and isinstance(mr, int) and mr > 0:
            diags.append(RULES["DTL203"].diag(
                f"min_checkpoint_period: 0 with max_restarts={mr}: mid-op "
                "failures can only restart from the previous op-boundary "
                "checkpoint (or from scratch); set a periodic "
                "min_checkpoint_period or max_restarts: 0"))
    return diags
