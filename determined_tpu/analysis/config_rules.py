"""Config cross-field checks (DTL2xx).

These run over the experiment-config dict alone — no trial code needed —
which is why the native master re-implements exactly this set in
native/master/preflight.cc and gates experiment creation on it. Keep the
two in lockstep: every rule added here must be added there (and to
docs/preflight.md).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from determined_tpu.analysis.diagnostics import Diagnostic
from determined_tpu.analysis.rules import RULES
from determined_tpu.parallel.mesh import AXIS_ORDER

# Axes the batch shards over (LogicalRules DEFAULT_RULES "batch" entry).
BATCH_AXES = ("data", "fsdp")

# DTL205's shape-affecting heuristic: an hparam whose snake_case tokens
# intersect this set changes tensor shapes when swept, so each distinct
# value compiles its own executable. Mirrored in native/master/preflight.cc
# — keep the two in lockstep.
SHAPE_HPARAM_TOKENS = frozenset({
    "batch", "size", "dim", "dims", "width", "depth", "layer", "layers",
    "head", "heads", "seq", "len", "length", "vocab", "position",
    "positions", "expert", "experts", "hidden", "model", "feature",
    "features", "channel", "channels", "embed", "embedding",
})

# "More distinct values than anyone could mean": double/log sweeps of a
# shape-affecting hparam without `count` are effectively unbounded.
_UNBOUNDED = 10**9


def is_shape_hparam(name: str) -> bool:
    return bool(SHAPE_HPARAM_TOKENS & set(name.lower().split("_")))


def _length_batches(v: Any) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, dict):
        for unit in ("batches", "records", "epochs"):
            if unit in v:
                return int(v[unit])
    return 0


def resolve_batch_axes_product(config: Dict[str, Any],
                               slots: Any = None) -> int:
    """data*fsdp resolved against slots_per_trial, mirroring
    MeshConfig.resolve (omitted `data` = -1 absorbs remaining chips).
    Returns 0 when the mesh is unresolvable (other validation reports it).
    `slots` overrides resources.slots_per_trial — the DTL204 elastic check
    re-resolves the same mesh at every candidate size.
    """
    hp = config.get("hyperparameters") or {}
    mesh = hp.get("mesh") or {}
    if not isinstance(mesh, dict):
        return 0
    res = config.get("resources") or {}
    if slots is None:
        slots = res.get("slots_per_trial", 1)
    if not isinstance(slots, int) or slots <= 0:
        return 0
    sizes = {a: 1 for a in AXIS_ORDER}
    unknown = []
    for a, v in mesh.items():
        if a not in sizes or isinstance(v, bool) or not isinstance(v, int):
            return 0
        if v == -1:
            unknown.append(a)
        elif v > 0:
            sizes[a] = v
        else:
            return 0
    if "data" not in mesh:
        unknown.append("data")
    if len(unknown) > 1:
        return 0
    fixed = math.prod(sizes[a] for a in AXIS_ORDER if a not in unknown)
    if unknown:
        if slots % fixed != 0:
            return 0
        sizes[unknown[0]] = slots // fixed
    elif fixed != slots:
        return 0
    return sizes["data"] * sizes["fsdp"]


def check_config(config: Dict[str, Any]) -> List[Diagnostic]:
    """DTL201 + DTL202 over a (shimmed) experiment config."""
    diags: List[Diagnostic] = []
    if not isinstance(config, dict):
        return diags

    # DTL201 — global_batch_size vs mesh batch axes.
    hp = config.get("hyperparameters") or {}
    gbs = hp.get("global_batch_size") if isinstance(hp, dict) else None
    if isinstance(gbs, dict):  # hparam spec {type: const, val: N}
        gbs = gbs.get("val") if gbs.get("type") == "const" else None
    if isinstance(gbs, int) and gbs > 0:
        bprod = resolve_batch_axes_product(config)
        if bprod > 1 and gbs % bprod != 0:
            diags.append(RULES["DTL201"].diag(
                f"hyperparameters.global_batch_size={gbs} is not divisible "
                f"by the mesh batch axes data x fsdp = {bprod} (resolved "
                f"against resources.slots_per_trial="
                f"{(config.get('resources') or {}).get('slots_per_trial', 1)})"))

    # DTL202 — ASHA budget vs rungs.
    searcher = config.get("searcher")
    if isinstance(searcher, dict) and searcher.get("name") in (
            "async_halving", "sync_halving"):
        max_length = _length_batches(searcher.get("max_length"))
        num_rungs = searcher.get("num_rungs") or 0
        divisor = searcher.get("divisor") or 4
        if max_length > 0 and isinstance(num_rungs, int) and num_rungs > 1 \
                and isinstance(divisor, (int, float)) and divisor > 1:
            bottom = max_length / (divisor ** (num_rungs - 1))
            if bottom < 1:
                diags.append(RULES["DTL202"].diag(
                    f"searcher.max_length={max_length} < divisor^(num_rungs-1)"
                    f"={int(divisor)}^{num_rungs - 1}="
                    f"{int(divisor ** (num_rungs - 1))}: the bottom rung "
                    "would train for zero batches and the top rungs are "
                    "unreachable; lower num_rungs or raise max_length"))

    # DTL204 — elastic configs must be runnable at EVERY size in
    # [min_slots, max_slots]: the scheduler may re-mesh the trial to any
    # of them on a drain or a scale-up (docs/elasticity.md). Mesh
    # resolvability + batch divisibility here; the HBM-per-size leg runs
    # in preflight() with the abstract-trace engine per candidate mesh.
    res = config.get("resources") or {}
    elastic = res.get("elastic") if isinstance(res, dict) else None
    if isinstance(elastic, dict):
        spt = res.get("slots_per_trial", 1)
        mn = elastic.get("min_slots", 1)
        mx = elastic.get("max_slots", spt if isinstance(spt, int) else 0)
        if isinstance(mn, int) and isinstance(mx, int) and 1 <= mn <= mx:
            gbs_val = gbs if isinstance(gbs, int) and gbs > 0 else None
            for k in range(mn, mx + 1):
                bprod = resolve_batch_axes_product(config, slots=k)
                if bprod == 0:
                    diags.append(RULES["DTL204"].diag(
                        f"elastic size {k} (of [{mn}, {mx}]): "
                        "hyperparameters.mesh does not resolve at this slot "
                        "count — the fixed axes product must divide every "
                        "size the scheduler may shrink/grow the trial to"))
                elif gbs_val is not None and gbs_val % bprod != 0:
                    diags.append(RULES["DTL204"].diag(
                        f"elastic size {k} (of [{mn}, {mx}]): "
                        f"hyperparameters.global_batch_size={gbs_val} is not "
                        f"divisible by the mesh batch axes data x fsdp = "
                        f"{bprod} at this slot count"))

    # DTL205 — shape-affecting hparam sweep without bucketing: more
    # distinct executables than compile.max_executables means the sweep
    # spends its trials compiling instead of training and the compile farm
    # can't share anything across them (docs/compile-farm.md).
    diags.extend(_check_shape_sweep(config))

    # DTL206 — serving paged-KV geometry (docs/serving.md "Paged KV &
    # prefix caching"): the block tables tile max_seq_len in
    # kv_block_size steps, so the block size must divide it; and an
    # explicit kv_num_blocks must leave room for at least one worst-case
    # sequence or admission can never succeed. Both fail the replica at
    # runtime — catch them before launch.
    serving = config.get("serving")
    if isinstance(serving, dict):
        bs = serving.get("kv_block_size", 16)
        max_seq = serving.get("max_seq_len", 256)
        nb = serving.get("kv_num_blocks")
        impl = serving.get("attention_impl", "auto")
        paged = impl != "dense"
        ok_ints = (isinstance(bs, int) and not isinstance(bs, bool)
                   and bs > 0 and isinstance(max_seq, int)
                   and not isinstance(max_seq, bool) and max_seq > 0)
        if paged and ok_ints:
            if max_seq % bs != 0:
                diags.append(RULES["DTL206"].diag(
                    f"serving.kv_block_size={bs} does not divide "
                    f"serving.max_seq_len={max_seq}: the paged block "
                    "tables tile max_seq_len exactly; pick a block size "
                    "that divides it"))
            elif (isinstance(nb, int) and not isinstance(nb, bool)
                  and nb > 0 and nb * bs < max_seq):
                diags.append(RULES["DTL206"].diag(
                    f"serving.kv_num_blocks={nb} x kv_block_size={bs} = "
                    f"{nb * bs} tokens of paged KV pool cannot hold even "
                    f"one max_seq_len={max_seq} sequence — no request "
                    "could ever be admitted; raise kv_num_blocks or lower "
                    "max_seq_len"))

    # DTL207 — capacity-loop knobs (docs/cluster-ops.md "Capacity loop"):
    # the scale-to-zero / spot-floor configuration must be satisfiable, or
    # the deployment either can't be created (master re-check) or pins
    # behavior the operator didn't mean (a floor above max would force
    # every replica on-demand forever).
    if isinstance(serving, dict) and isinstance(serving.get("replicas"),
                                                dict):
        rep = serving["replicas"]

        def _int(key, default):
            v = rep.get(key, default)
            return v if isinstance(v, int) and not isinstance(v, bool) \
                else default

        mn = _int("min", 1)
        tgt = _int("target", mn)
        mx = _int("max", max(1, mn, tgt))
        if mn < 0:
            diags.append(RULES["DTL207"].diag(
                f"serving.replicas.min={mn} is negative; 0 "
                "(scale-to-zero) is the smallest legal floor"))
        elif mn > mx:
            diags.append(RULES["DTL207"].diag(
                f"serving.replicas.min={mn} exceeds max={mx}"))
        floor = rep.get("on_demand_floor", max(mn, 0))
        if isinstance(floor, int) and not isinstance(floor, bool) and (
                floor < 0 or floor > mx):
            diags.append(RULES["DTL207"].diag(
                f"serving.replicas.on_demand_floor={floor} must be within "
                f"[0, max={mx}]: a floor above max can never be satisfied "
                "and would pin every replica to on-demand capacity"))
        budget = rep.get("cold_start_budget_s")
        if budget is not None and (
                isinstance(budget, bool)
                or not isinstance(budget, (int, float)) or budget <= 0):
            diags.append(RULES["DTL207"].diag(
                "serving.replicas.cold_start_budget_s must be a positive "
                "number of seconds: it bounds how long the router holds a "
                "request while a scale-from-zero replica restores"))

    # DTL208 — canary traffic fraction (docs/serving.md "Model
    # lifecycle"): a config-declared canary must split a REAL fraction of
    # traffic — 0 burns a replica for no signal, 1 is a rollout wearing a
    # canary costume (use `det serve update`). Mirrored in
    # native/master/preflight.cc; the deployment-create gate enforces it.
    if isinstance(serving, dict) and isinstance(serving.get("canary"), dict):
        cb = serving["canary"]
        frac = cb.get("fraction")
        if frac is not None and (
                isinstance(frac, bool) or not isinstance(frac, (int, float))
                or not 0 < frac < 1):
            diags.append(RULES["DTL208"].diag(
                f"serving.canary.fraction={frac!r} must be strictly "
                "inside (0, 1): 0 routes nothing to the canary and 1 is "
                "a full rollout — use `det serve update` for that"))

    # DTL203 — restarts configured but nothing to restart from. Only an
    # EXPLICIT min_checkpoint_period: 0 fires (key present): the default is
    # also 0 batches and flagging every config would be pure noise.
    if "min_checkpoint_period" in config:
        mcp = _length_batches(config.get("min_checkpoint_period"))
        mr = config.get("max_restarts", 5)
        if mcp == 0 and isinstance(mr, int) and mr > 0:
            diags.append(RULES["DTL203"].diag(
                f"min_checkpoint_period: 0 with max_restarts={mr}: mid-op "
                "failures can only restart from the previous op-boundary "
                "checkpoint (or from scratch); set a periodic "
                "min_checkpoint_period or max_restarts: 0"))
    return diags


def _distinct_bucketed_batches(mn: int, mx: int, buckets) -> int:
    """Distinct bucket boundaries an int range [mn, mx] maps onto."""
    from determined_tpu.compile.bucketing import bucket_size

    n, b = 0, mn
    while b <= mx and n <= 64:
        n += 1
        b = max(bucket_size(b, buckets), b) + 1
    return max(1, n)


def _spec_distinct(name: str, spec: Any, cfg) -> Tuple[int, bool]:
    """(distinct executable shapes this spec sweeps to, bucketing_helped).
    Non-spec values and consts count 1."""
    from determined_tpu.compile.bucketing import bucket_size

    if not isinstance(spec, dict) or not isinstance(spec.get("type"), str):
        return 1, False
    t = spec["type"]
    is_gbs = name == "global_batch_size"
    if t == "const":
        return 1, False
    if t == "categorical":
        vals = spec.get("vals") or []
        if is_gbs and cfg.bucket_batch_sizes:
            ints = [v for v in vals
                    if isinstance(v, int) and not isinstance(v, bool)]
            if ints:
                return len({bucket_size(v, cfg.buckets) for v in ints}), True
        return max(1, len(vals)), False
    if t == "int":
        mn, mx = spec.get("minval"), spec.get("maxval")
        if not isinstance(mn, int) or not isinstance(mx, int) or mx < mn:
            return 1, False
        if is_gbs and cfg.bucket_batch_sizes:
            return _distinct_bucketed_batches(mn, mx, cfg.buckets), True
        cnt = spec.get("count")
        if isinstance(cnt, int) and cnt > 0:
            return min(cnt, mx - mn + 1), False
        return mx - mn + 1, False
    # double/log sweeping a shape-affecting hparam: every sample is a new
    # shape unless `count` bounds it.
    cnt = spec.get("count")
    if isinstance(cnt, int) and cnt > 0:
        return cnt, False
    return _UNBOUNDED, False


def _check_shape_sweep(config: Dict[str, Any]) -> List[Diagnostic]:
    """DTL205 (docs/compile-farm.md): estimate the distinct executables a
    sweep implies from its shape-affecting hparams and warn past
    compile.max_executables when bucketing is off for the offenders."""
    from determined_tpu.compile.bucketing import CompileConfig

    searcher = config.get("searcher")
    if not isinstance(searcher, dict) or searcher.get("name") in (
            "single", "custom", None):
        return []
    hp = config.get("hyperparameters")
    if not isinstance(hp, dict):
        return []
    cfg = CompileConfig.from_block(config.get("compile"))
    total = 1
    offenders: List[str] = []
    bucketable = False
    for name, spec in hp.items():
        if name == "mesh" or not is_shape_hparam(name):
            continue
        n, bucketed = _spec_distinct(name, spec, cfg)
        if n > 1:
            offenders.append(f"{name} ({'unbounded' if n >= _UNBOUNDED else n}"
                             " distinct shapes)")
            total = min(total * n, _UNBOUNDED)
            if name == "global_batch_size" and not bucketed:
                bucketable = True
    max_trials = searcher.get("max_trials")
    if isinstance(max_trials, int) and max_trials > 0:
        total = min(total, max_trials)
    if not offenders or total <= cfg.max_executables:
        return []
    hint = ("enable compile.bucket_batch_sizes so batch sizes share "
            "bucketed executables, " if bucketable else "")
    return [RULES["DTL205"].diag(
        f"searcher sweep implies ~{'unbounded' if total >= _UNBOUNDED else total} "
        f"distinct executables from shape-affecting hyperparameters "
        f"[{', '.join(offenders)}] > compile.max_executables="
        f"{cfg.max_executables}: each distinct shape pays a full XLA "
        f"compile and the compile farm cannot share artifacts across them; "
        f"{hint}use const/categorical values, or raise "
        "compile.max_executables if intended")]
