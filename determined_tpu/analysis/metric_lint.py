"""Metric- and span-name lint (docs/observability.md).

`determined_tpu/common/metric_names.py` is the single source of truth for
every exported Prometheus metric name and every lifecycle-span name. This
lint keeps the master (C++), agent (C++), serving replicas and harness
from drifting apart on the same gauge, in BOTH directions:

  - every `det_*` name emitted in the scanned sources must be registered;
  - every registered name must still be emitted somewhere (a stale
    registry row is drift too);
  - the registry itself must satisfy the naming rules (snake_case,
    `_total` counters, unit suffixes on measured quantities).

Emission sites are found syntactically: `det_*` tokens inside string
literals for metrics; `*.span("...")` / `*.emit("...")` / `._span("...")`
(Python) and `make_span(..., "...")` (C++) call sites for spans. Run by
`make lint` via `python -m determined_tpu.analysis`.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set

from determined_tpu.common import metric_names

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Everything that renders Prometheus exposition text. Registry drift in an
# unlisted new emitter is caught the day its names are added here — adding
# the file to this list is part of adding the endpoint.
METRIC_SOURCES = [
    "native/master/master.cc",
    "native/agent/main.cc",
    "determined_tpu/serve/http.py",
]

# Everything that emits lifecycle or request spans.
SPAN_SOURCES = [
    "native/master/master_experiments.cc",
    "native/master/master_agents.cc",
    "native/master/master_deployments.cc",
    "native/agent/main.cc",
    "determined_tpu/train/trainer.py",
    "determined_tpu/core/_checkpoint.py",
    "determined_tpu/serve/tracing.py",
]

_STRING_RE = re.compile(r'"((?:[^"\\\n]|\\.)*)"')
# (?<![.\w]) keeps filenames like ".det_status" out of the metric scan.
_METRIC_TOKEN_RE = re.compile(r"(?<![.\w])det(?:_[a-z0-9]+)+\b")
# Histogram series derive these at exposition time; strip before lookup.
_HIST_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")
_PY_SPAN_RE = re.compile(r'(?:\bspan|\bemit|_span)\(\s*"([a-z0-9_.]+)"')
_CC_SPAN_RE = re.compile(r'make_span\(\s*[^"]*?"([a-z0-9_.]+)"')


def _read(relpath: str, root: str = REPO_ROOT) -> str:
    with open(os.path.join(root, relpath)) as f:
        return f.read()


def _emitted_metrics(text: str) -> Set[str]:
    found: Set[str] = set()
    for m in _STRING_RE.finditer(text):
        for tok in _METRIC_TOKEN_RE.findall(m.group(1)):
            found.add(_HIST_SUFFIX_RE.sub("", tok))
    return found


def _emitted_spans(relpath: str, text: str) -> Set[str]:
    pattern = _CC_SPAN_RE if relpath.endswith(".cc") else _PY_SPAN_RE
    return {name for name in pattern.findall(text) if "." in name}


def lint_registry(root: str = REPO_ROOT) -> List[str]:
    """Returns violation strings (empty = clean). Missing source files are
    violations too — a renamed emitter must update the scan list."""
    problems = list(metric_names.check_registry())

    emitted_metrics: Dict[str, Set[str]] = {}
    for rel in METRIC_SOURCES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: metric source missing (update "
                            "analysis/metric_lint.py METRIC_SOURCES)")
            continue
        emitted_metrics[rel] = _emitted_metrics(_read(rel, root))

    registered = set(metric_names.all_metrics())
    all_emitted: Set[str] = set()
    for rel, names in emitted_metrics.items():
        all_emitted |= names
        for name in sorted(names - registered):
            problems.append(
                f"{rel}: metric {name!r} emitted but not registered in "
                "common/metric_names.py")
    for name in sorted(registered - all_emitted):
        problems.append(
            f"common/metric_names.py: metric {name!r} registered but "
            "emitted nowhere (stale registry row)")

    emitted_spans: Set[str] = set()
    for rel in SPAN_SOURCES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: span source missing (update "
                            "analysis/metric_lint.py SPAN_SOURCES)")
            continue
        names = _emitted_spans(rel, _read(rel, root))
        for name in sorted(names - set(metric_names.SPAN_NAMES)):
            problems.append(
                f"{rel}: span {name!r} emitted but not registered in "
                "common/metric_names.py SPAN_NAMES")
        emitted_spans |= names
    for name in sorted(set(metric_names.SPAN_NAMES) - emitted_spans):
        problems.append(
            f"common/metric_names.py: span {name!r} registered but emitted "
            "nowhere (stale registry row)")
    return problems


def main() -> int:
    problems = lint_registry()
    for p in problems:
        print(f"metric-lint: {p}")
    print(f"metric-lint: {len(problems)} finding(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
