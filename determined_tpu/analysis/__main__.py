"""Tree lint entry point — `python -m determined_tpu.analysis [paths...]`.

Runs the AST engine (DTL1xx) over source trees; exits 1 on any unsuppressed
finding. This is what `make lint` at the repo root runs over determined_tpu/
and examples/ so the platform's own models stay clean against its own rules
(the dogfood gate).
"""

from __future__ import annotations

import argparse
import json
import sys

from determined_tpu.analysis import astlint


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m determined_tpu.analysis",
                                description=__doc__)
    p.add_argument("paths", nargs="*", default=["determined_tpu", "examples"],
                   help="files or directories to lint (default: "
                        "determined_tpu examples)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--no-metric-lint", action="store_true",
                   help="skip the metric/span name registry check")
    p.add_argument("--no-native-lint", action="store_true",
                   help="skip the native locking-convention / registry "
                        "check (docs/static-analysis.md)")
    args = p.parse_args(argv)

    diags = astlint.lint_paths(args.paths or ["determined_tpu", "examples"])
    active = [d for d in diags if not d.suppressed]

    # Metric/span-name registry check (docs/observability.md): master,
    # agent, serve and harness must agree with common/metric_names.py.
    metric_problems = []
    if not args.as_json and not args.no_metric_lint:
        from determined_tpu.analysis import metric_lint

        metric_problems = metric_lint.lint_registry()
        for prob in metric_problems:
            print(f"metric-lint: {prob}")

    # Native locking conventions + cross-language registries
    # (docs/static-analysis.md): the textual half of the thread-safety
    # gate — `make -C native tsa` is the compile-time half.
    native_problems = []
    if not args.as_json and not args.no_native_lint:
        from determined_tpu.analysis import native_lint

        native_problems = native_lint.lint_native()
        for prob in native_problems:
            print(f"native-lint: {prob}")
    if args.as_json:
        print(json.dumps([d.to_dict() for d in diags], indent=2))
    else:
        for d in diags:
            tag = f"{d.level} {d.code}"
            if d.suppressed:
                tag += " (suppressed)"
            print(f"{d.location()}: {tag}: {d.message}")
        n_sup = len(diags) - len(active)
        from determined_tpu.analysis import native_lint as _nl

        print(f"lint: {len(active)} finding(s), {n_sup} suppressed; "
              f"metric-lint: {len(metric_problems)} finding(s); "
              f"native-lint: {len(native_problems)} finding(s), "
              f"{_nl.tsa_escape_count()}/{_nl.MAX_TSA_ESCAPES} tsa escapes")
    return 1 if active or metric_problems or native_problems else 0


if __name__ == "__main__":
    sys.exit(main())
