"""Framework integrations (reference: harness/determined/transformers/ and
model_hub/)."""
