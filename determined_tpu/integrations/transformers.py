"""DetCallback — HuggingFace Trainer bridge.

Reference: harness/determined/transformers/_hf_callback.py:14 — a
`transformers.TrainerCallback` that reports train/eval metrics to the Core
API (:69,:80), drives searcher ops (:31-48,:90), uploads HF checkpoints
(:111-132) and honors preemption (:97). This is the north-star GPT-2
workload path (examples/hf_trainer_api).

On TPU the HF Trainer runs via torch-xla when available; the callback is
backend-agnostic — it only speaks the Core API.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

import transformers

from determined_tpu import core

logger = logging.getLogger("determined_tpu.integrations.transformers")


class DetCallback(transformers.TrainerCallback):
    def __init__(
        self,
        core_context: core.Context,
        args: Optional[transformers.TrainingArguments] = None,
        metric_name: Optional[str] = None,
    ) -> None:
        self.core = core_context
        self.metric_name = metric_name or self._searcher_metric()
        self.last_eval: Dict[str, Any] = {}
        self.searcher_ops = None
        self.current_op = None

    def _searcher_metric(self) -> Optional[str]:
        info = self.core.info
        if info and info.trial:
            return info.trial.config.get("searcher", {}).get("metric")
        return None

    # -- searcher ops (reference :31-48) --------------------------------
    def _ensure_op(self, state: transformers.TrainerState,
                   control: transformers.TrainerControl) -> None:
        if self.searcher_ops is None:
            self.searcher_ops = self.core.searcher.operations()
        if self.current_op is None:
            try:
                self.current_op = next(self.searcher_ops)
            except StopIteration:
                control.should_training_stop = True

    def on_step_end(self, args, state, control, **kwargs):
        self._ensure_op(state, control)
        if self.current_op is not None and state.global_step >= self.current_op.length:
            control.should_evaluate = True
        # Preemption (reference :97): checkpoint then stop.
        if self.core.preempt.should_preempt():
            control.should_save = True
            control.should_training_stop = True
        return control

    # -- metrics (reference :69,:80) ------------------------------------
    def on_log(self, args, state, control, logs=None, **kwargs):
        if not logs:
            return
        metrics = {k: v for k, v in logs.items()
                   if isinstance(v, (int, float))}
        if any(k.startswith("eval_") for k in metrics):
            self.core.train.report_validation_metrics(state.global_step, metrics)
        else:
            self.core.train.report_training_metrics(state.global_step, metrics)

    def on_evaluate(self, args, state, control, metrics=None, **kwargs):
        metrics = metrics or {}
        self.last_eval = metrics
        self.core.train.report_validation_metrics(state.global_step, metrics)
        self._ensure_op(state, control)
        if self.current_op is not None and state.global_step >= self.current_op.length:
            name = self.metric_name or "eval_loss"
            if name not in metrics:
                if "eval_loss" not in metrics:
                    raise KeyError(
                        f"searcher metric {name!r} not in eval metrics "
                        f"{sorted(metrics)}"
                    )
                logger.warning("searcher metric %r missing; using eval_loss", name)
                name = "eval_loss"
            self.current_op.report_completed(float(metrics[name]))
            self.current_op = None
            self._ensure_op(state, control)
            if self.current_op is None:
                control.should_training_stop = True
        return control

    # -- checkpoints (reference :111-132) -------------------------------
    def on_save(self, args, state, control, **kwargs):
        ckpt_dir = transformers.trainer_utils.get_last_checkpoint(args.output_dir)
        if ckpt_dir is None:
            return
        storage_id = self.core.checkpoint.upload(
            ckpt_dir,
            metadata={
                "steps_completed": state.global_step,
                "framework": "transformers",
                "hf_checkpoint_name": os.path.basename(ckpt_dir),
            },
            shard=self.core.distributed is not None
            and self.core.distributed.size > 1,
        )
        logger.info("uploaded HF checkpoint %s as %s", ckpt_dir, storage_id)

    @staticmethod
    def resume_checkpoint_dir(core_context: core.Context, local_dir: str) -> Optional[str]:
        """Download info.latest_checkpoint for Trainer(resume_from_checkpoint=…)."""
        latest = core_context.latest_checkpoint
        if not latest:
            return None
        dest = os.path.join(local_dir, latest)
        core_context.checkpoint.download(latest, dest)
        return dest
