"""determined-tpu: a TPU-native deep-learning training platform.

A ground-up JAX/XLA/Pallas re-design with the capability surface of the
Determined AI platform (reference: arnaudfroidmont/determined): distributed
training, hyperparameter search, cluster resource management, and experiment
tracking — built TPU-first.

Layering (bottom → top):
  - ``determined_tpu.parallel``  — device meshes, logical sharding rules, collectives
  - ``determined_tpu.ops``       — pallas TPU kernels (flash/ring attention, ...)
  - ``determined_tpu.models``    — reference model families (GPT-2, ResNet, MNIST)
  - ``determined_tpu.train``     — Trial/Trainer APIs (the JAX-native analogue of
                                   the reference's PyTorchTrial/Trainer,
                                   harness/determined/pytorch/_trainer.py)
  - ``determined_tpu.core``      — Core API: train/searcher/checkpoint/preempt
                                   contexts (reference harness/determined/core/)
  - ``determined_tpu.searcher``  — HP-search state machines (reference
                                   master/pkg/searcher/)
  - ``determined_tpu.expconf``   — experiment-config schema system (reference
                                   master/pkg/schemas/expconf/)
  - ``determined_tpu.master``    — control plane: API server, experiment/trial
                                   state machines, topology-aware scheduler
  - ``determined_tpu.agent``     — TPU-VM host daemon: chip detection, task launch
  - ``determined_tpu.cli``       — the ``det`` command
"""

__version__ = "0.1.0"

from determined_tpu._info import ClusterInfo, get_cluster_info  # noqa: F401
