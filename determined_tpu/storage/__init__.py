"""Checkpoint storage backends (reference harness/determined/common/storage/).

A StorageManager maps (storage config) → concrete paths/upload/download.
`shared_fs` and `directory` are fully native (GCS buckets are typically
FUSE-mounted on TPU-VMs, so shared_fs covers gcsfuse too); `gcs`/`s3` use
their cloud SDKs when importable and raise a clear error otherwise; `azure`
speaks the Blob REST protocol directly (storage/azure.py, no SDK needed).
"""

from determined_tpu.storage.base import StorageManager, from_config  # noqa: F401
