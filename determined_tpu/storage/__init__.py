"""Checkpoint storage backends (reference harness/determined/common/storage/).

A StorageManager maps (storage config) → concrete paths/upload/download.
`shared_fs` and `directory` are fully native (GCS buckets are typically
FUSE-mounted on TPU-VMs, so shared_fs covers gcsfuse too); `gcs`/`s3`/`azure`
use their cloud SDKs when importable and raise a clear error otherwise.
"""

from determined_tpu.storage.base import StorageManager, from_config  # noqa: F401
