"""Cloud storage backends: GCS / S3 / Azure (reference
harness/determined/common/storage/{gcs,s3,azure}.py).

On TPU-VMs the canonical checkpoint path is a GCS bucket. Two modes:
  1. tensorstore-native: orbax writes `gs://...` URLs directly (no local
     staging) — used automatically by CheckpointContext when the storage
     manager exposes a `url_for` returning a gs:// path.
  2. SDK copy mode: upload/download via the cloud SDK, for arbitrary files.
SDKs are imported lazily; a missing SDK raises with install guidance.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List, Optional

from determined_tpu.storage.base import StorageManager


class CloudStorageManager(StorageManager):
    scheme = ""

    def __init__(self, bucket: str, prefix: str = ""):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        # local staging area for upload/download-style use
        super().__init__(os.path.join(tempfile.gettempdir(), "det_tpu_cloud_staging"))

    def url_for(self, storage_id: str) -> str:
        parts = [p for p in (self.bucket, self.prefix, storage_id) if p]
        return f"{self.scheme}://" + "/".join(parts)


class GCSStorageManager(CloudStorageManager):
    scheme = "gs"

    def __init__(self, bucket: str, prefix: str = ""):
        super().__init__(bucket, prefix)
        try:
            from google.cloud import storage as _  # noqa: F401

            self._sdk = True
        except ImportError:
            # tensorstore can still write gs:// URLs without the SDK.
            self._sdk = False

    def upload(self, src: str, storage_id: str, paths: Optional[List[str]] = None) -> None:
        if not self._sdk:
            raise RuntimeError(
                "google-cloud-storage not installed; array checkpoints still "
                "work via tensorstore gs:// paths, but file upload needs the SDK"
            )
        from google.cloud import storage

        client = storage.Client()
        bucket = client.bucket(self.bucket)
        names = paths if paths is not None else os.listdir(src)
        for name in names:
            full = os.path.join(src, name)
            if os.path.isdir(full):
                for root, _, files in os.walk(full):
                    for f in files:
                        p = os.path.join(root, f)
                        rel = os.path.relpath(p, src)
                        bucket.blob(self._key(storage_id, rel)).upload_from_filename(p)
            else:
                bucket.blob(self._key(storage_id, name)).upload_from_filename(full)

    def download(self, storage_id: str, dst: str, selector=None) -> None:
        if not self._sdk:
            raise RuntimeError("google-cloud-storage not installed")
        from google.cloud import storage

        client = storage.Client()
        bucket = client.bucket(self.bucket)
        prefix = self._key(storage_id, "")
        for blob in client.list_blobs(bucket, prefix=prefix):
            rel = blob.name[len(prefix):]
            if selector is not None and not selector(rel):
                continue
            out = os.path.join(dst, rel)
            os.makedirs(os.path.dirname(out), exist_ok=True)
            blob.download_to_filename(out)

    def _key(self, storage_id: str, rel: str) -> str:
        parts = [p for p in (self.prefix, storage_id, rel) if p]
        return "/".join(parts)


class S3StorageManager(CloudStorageManager):
    scheme = "s3"

    def __init__(self, bucket: str, prefix: str = ""):
        super().__init__(bucket, prefix)
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise RuntimeError("boto3 not installed; s3 storage unavailable") from e

    def upload(self, src: str, storage_id: str, paths: Optional[List[str]] = None) -> None:
        import boto3

        s3 = boto3.client("s3")
        names = paths if paths is not None else os.listdir(src)
        for name in names:
            full = os.path.join(src, name)
            if os.path.isdir(full):
                for root, _, files in os.walk(full):
                    for f in files:
                        p = os.path.join(root, f)
                        rel = os.path.relpath(p, src)
                        s3.upload_file(p, self.bucket, self._key(storage_id, rel))
            else:
                s3.upload_file(full, self.bucket, self._key(storage_id, name))

    def download(self, storage_id: str, dst: str, selector=None) -> None:
        import boto3

        s3 = boto3.client("s3")
        prefix = self._key(storage_id, "")
        paginator = s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                rel = obj["Key"][len(prefix):]
                if selector is not None and not selector(rel):
                    continue
                out = os.path.join(dst, rel)
                os.makedirs(os.path.dirname(out), exist_ok=True)
                s3.download_file(self.bucket, obj["Key"], out)

    def _key(self, storage_id: str, rel: str) -> str:
        parts = [p for p in (self.prefix, storage_id, rel) if p]
        return "/".join(parts)


class AzureStorageManager(CloudStorageManager):
    scheme = "az"

    def __init__(self, container: str, connection_string: str = "", prefix: str = ""):
        super().__init__(container, prefix)
        raise RuntimeError(
            "azure-storage-blob not available in this image; use shared_fs/gcs"
        )


def cloud_from_config(stype: str, config: Dict[str, Any]) -> StorageManager:
    if stype == "gcs":
        return GCSStorageManager(config["bucket"], config.get("prefix", ""))
    if stype == "s3":
        return S3StorageManager(config["bucket"], config.get("prefix", ""))
    if stype == "azure":
        return AzureStorageManager(config.get("container", ""), config.get("connection_string", ""))
    raise ValueError(stype)
