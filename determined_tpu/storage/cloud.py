"""Cloud storage backends: GCS / S3 / Azure (reference
harness/determined/common/storage/{gcs,s3,azure}.py).

On TPU-VMs the canonical checkpoint path is a GCS bucket. Two modes:
  1. tensorstore-native: orbax writes `gs://...` URLs directly (no local
     staging) — used automatically by CheckpointContext when the storage
     manager exposes a `url_for` returning a gs:// path.
  2. staged-copy mode: `store_path` yields a local staging dir and uploads it
     on exit; `restore_path` downloads into staging first. This is how file
     checkpoints (keras .keras files, torch state dicts) reach the bucket,
     and how array checkpoints work on backends tensorstore has no driver
     for (azure).
SDKs are imported lazily; a missing SDK raises with install guidance.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import Any, Dict, Iterator, List, Optional, Tuple

from determined_tpu.storage.base import StorageManager


class CloudStorageManager(StorageManager):
    scheme = ""
    # File-style checkpoints stage locally and copy to the bucket; array
    # checkpoints skip staging iff url_for() returns a tensorstore URL.
    requires_staging = True

    def __init__(self, bucket: str, prefix: str = ""):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        # local staging area for upload/download-style use
        super().__init__(os.path.join(tempfile.gettempdir(), "det_tpu_cloud_staging"))

    def url_for(self, storage_id: str) -> Optional[str]:
        if not self.scheme:
            return None
        parts = [p for p in (self.bucket, self.prefix, storage_id) if p]
        return f"{self.scheme}://" + "/".join(parts)

    def _key(self, storage_id: str, rel: str) -> str:
        parts = [p for p in (self.prefix, storage_id, rel) if p]
        return "/".join(parts)

    def _list_prefix(self, storage_id: str) -> str:
        key = self._key(storage_id, "")
        return key + "/" if key and not key.endswith("/") else key

    @staticmethod
    def _iter_upload_files(src: str, paths: Optional[List[str]]) -> Iterator[Tuple[str, str]]:
        from determined_tpu.storage.base import iter_upload_files

        return iter_upload_files(src, paths)

    # -- staged file checkpoints --------------------------------------

    @contextlib.contextmanager
    def store_path(self, storage_id: Optional[str] = None) -> Iterator[tuple]:
        """Stage locally, upload to the bucket on exit (reference
        StorageManager.store_path upload-on-close semantics). Staging is
        removed after the upload so periodic checkpointing doesn't fill /tmp."""
        import shutil

        storage_id = storage_id or self.new_storage_id()
        path = self.path_for(storage_id)
        os.makedirs(path, exist_ok=True)
        try:
            yield storage_id, path
            self.upload(path, storage_id)
        finally:
            shutil.rmtree(path, ignore_errors=True)

    @contextlib.contextmanager
    def restore_path(self, storage_id: str) -> Iterator[str]:
        """Download into a FRESH staging dir (stale/partial staging from an
        earlier save on this host must never shadow the bucket), raise
        FileNotFoundError like the base class when the id doesn't exist, and
        clean staging up afterwards."""
        import shutil

        path = self.path_for(storage_id)
        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        try:
            self.download(storage_id, path)
            if not os.listdir(path):
                raise FileNotFoundError(
                    f"checkpoint {storage_id} not found in {type(self).__name__}"
                )
            yield path
        finally:
            shutil.rmtree(path, ignore_errors=True)


class GCSStorageManager(CloudStorageManager):
    scheme = "gs"

    def __init__(self, bucket: str, prefix: str = ""):
        super().__init__(bucket, prefix)
        try:
            from google.cloud import storage as _  # noqa: F401

            self._sdk = True
        except ImportError:
            # tensorstore can still write gs:// URLs without the SDK.
            self._sdk = False

    def upload(self, src: str, storage_id: str, paths: Optional[List[str]] = None) -> None:
        if not self._sdk:
            raise RuntimeError(
                "google-cloud-storage not installed; array checkpoints still "
                "work via tensorstore gs:// paths, but file upload needs the SDK"
            )
        from google.cloud import storage

        client = storage.Client()
        bucket = client.bucket(self.bucket)
        for path, rel in self._iter_upload_files(src, paths):
            bucket.blob(self._key(storage_id, rel)).upload_from_filename(path)

    def download(self, storage_id: str, dst: str, selector=None) -> None:
        if not self._sdk:
            raise RuntimeError("google-cloud-storage not installed")
        from google.cloud import storage

        client = storage.Client()
        bucket = client.bucket(self.bucket)
        prefix = self._list_prefix(storage_id)
        for blob in client.list_blobs(bucket, prefix=prefix):
            rel = blob.name[len(prefix):]
            if selector is not None and not selector(rel):
                continue
            out = os.path.join(dst, rel)
            os.makedirs(os.path.dirname(out) or dst, exist_ok=True)
            blob.download_to_filename(out)

    def list_files(self, storage_id: str) -> Dict[str, int]:
        if not self._sdk:
            return {}
        from google.cloud import storage

        client = storage.Client()
        prefix = self._list_prefix(storage_id)
        return {
            b.name[len(prefix):]: b.size or 0
            for b in client.list_blobs(client.bucket(self.bucket), prefix=prefix)
        }

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, Any]:
        if not self._sdk:
            raise RuntimeError("google-cloud-storage not installed")
        import fnmatch

        from google.cloud import storage

        client = storage.Client()
        bucket = client.bucket(self.bucket)
        prefix = self._list_prefix(storage_id)
        remaining: Dict[str, int] = {}
        for blob in client.list_blobs(bucket, prefix=prefix):
            rel = blob.name[len(prefix):]
            if globs is not None and not any(fnmatch.fnmatch(rel, g) for g in globs):
                remaining[rel] = blob.size or 0
                continue
            blob.delete()
        return remaining


class S3StorageManager(CloudStorageManager):
    scheme = "s3"

    def __init__(self, bucket: str, prefix: str = ""):
        super().__init__(bucket, prefix)
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise RuntimeError("boto3 not installed; s3 storage unavailable") from e

    def upload(self, src: str, storage_id: str, paths: Optional[List[str]] = None) -> None:
        import boto3

        s3 = boto3.client("s3")
        for path, rel in self._iter_upload_files(src, paths):
            s3.upload_file(path, self.bucket, self._key(storage_id, rel))

    def download(self, storage_id: str, dst: str, selector=None) -> None:
        import boto3

        s3 = boto3.client("s3")
        prefix = self._list_prefix(storage_id)
        paginator = s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                rel = obj["Key"][len(prefix):]
                if selector is not None and not selector(rel):
                    continue
                out = os.path.join(dst, rel)
                os.makedirs(os.path.dirname(out) or dst, exist_ok=True)
                s3.download_file(self.bucket, obj["Key"], out)

    def list_files(self, storage_id: str) -> Dict[str, int]:
        import boto3

        s3 = boto3.client("s3")
        prefix = self._list_prefix(storage_id)
        out: Dict[str, int] = {}
        paginator = s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                out[obj["Key"][len(prefix):]] = obj["Size"]
        return out

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, Any]:
        import fnmatch

        import boto3

        s3 = boto3.client("s3")
        prefix = self._list_prefix(storage_id)
        remaining: Dict[str, int] = {}
        doomed: List[str] = []
        for rel, size in self.list_files(storage_id).items():
            if globs is not None and not any(fnmatch.fnmatch(rel, g) for g in globs):
                remaining[rel] = size
                continue
            doomed.append(prefix + rel)
        # Sharded checkpoints hold thousands of tensorstore chunks — batch
        # deletes (1000 keys/request is the S3 API limit).
        for i in range(0, len(doomed), 1000):
            s3.delete_objects(
                Bucket=self.bucket,
                Delete={"Objects": [{"Key": k} for k in doomed[i : i + 1000]]},
            )
        return remaining


class AzureStorageManager(CloudStorageManager):
    """Azure Blob backend over the stdlib REST client (storage/azure.py) —
    no SDK dependency. `bucket` is the container name. tensorstore has no
    az:// driver, so url_for returns None and CheckpointContext uses the
    staged save+upload path for array checkpoints too."""

    scheme = ""  # no tensorstore scheme → url_for() → None → staged copies

    def __init__(self, container: str, connection_string: str = "", prefix: str = ""):
        super().__init__(container, prefix)
        from determined_tpu.storage.azure import AzureBlobClient

        self._client = AzureBlobClient(connection_string or None)

    def upload(self, src: str, storage_id: str, paths: Optional[List[str]] = None) -> None:
        for path, rel in self._iter_upload_files(src, paths):
            self._client.put_blob_from_file(
                self.bucket, self._key(storage_id, rel), path
            )

    def download(self, storage_id: str, dst: str, selector=None) -> None:
        prefix = self._list_prefix(storage_id)
        for name, _size in self._client.list_blobs(self.bucket, prefix):
            rel = name[len(prefix):]
            if selector is not None and not selector(rel):
                continue
            out = os.path.join(dst, rel)
            os.makedirs(os.path.dirname(out) or dst, exist_ok=True)
            self._client.get_blob_to_file(self.bucket, name, out)

    def list_files(self, storage_id: str) -> Dict[str, int]:
        prefix = self._list_prefix(storage_id)
        return {
            name[len(prefix):]: size
            for name, size in self._client.list_blobs(self.bucket, prefix)
        }

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, Any]:
        import fnmatch

        prefix = self._list_prefix(storage_id)
        remaining: Dict[str, int] = {}
        for name, size in self._client.list_blobs(self.bucket, prefix):
            rel = name[len(prefix):]
            if globs is not None and not any(fnmatch.fnmatch(rel, g) for g in globs):
                remaining[rel] = size
                continue
            self._client.delete_blob(self.bucket, name)
        return remaining


def cloud_from_config(stype: str, config: Dict[str, Any]) -> StorageManager:
    if stype == "gcs":
        return GCSStorageManager(config["bucket"], config.get("prefix", ""))
    if stype == "s3":
        return S3StorageManager(config["bucket"], config.get("prefix", ""))
    if stype == "azure":
        if not config.get("container"):
            raise ValueError(
                "checkpoint_storage.container is required for azure storage"
            )
        return AzureStorageManager(
            config["container"],
            config.get("connection_string", ""),
            config.get("prefix", ""),
        )
    raise ValueError(stype)
