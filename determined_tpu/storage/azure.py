"""Azure Blob storage backend — stdlib-only REST client.

Reference: harness/determined/common/storage/azure.py (which uses
azure-storage-blob). The SDK is not available in TPU task images, so this
implements the Blob service REST protocol directly (PUT/GET/DELETE blob +
List Blobs) with Shared Key authorization (HMAC-SHA256 over the canonical
string-to-sign). Works against real Azure endpoints and local emulators
(Azurite / the fake server in tests) via the `BlobEndpoint` connection-string
key.
"""

from __future__ import annotations

import base64
import email.utils
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

_API_VERSION = "2021-08-06"


def parse_connection_string(cs: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in cs.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k] = v
    return out


class AzureBlobClient:
    """Minimal Blob-service client: shared-key signed PUT/GET/DELETE/LIST."""

    def __init__(self, connection_string: Optional[str] = None):
        cs = connection_string or os.environ.get("AZURE_STORAGE_CONNECTION_STRING", "")
        if not cs:
            raise ValueError(
                "azure storage needs a connection_string (config key or "
                "AZURE_STORAGE_CONNECTION_STRING)"
            )
        parts = parse_connection_string(cs)
        self.account = parts.get("AccountName", "")
        key = parts.get("AccountKey", "")
        self.key = base64.b64decode(key) if key else b""
        if "BlobEndpoint" in parts:
            self.endpoint = parts["BlobEndpoint"].rstrip("/")
        else:
            proto = parts.get("DefaultEndpointsProtocol", "https")
            suffix = parts.get("EndpointSuffix", "core.windows.net")
            if not self.account:
                raise ValueError("connection string missing AccountName")
            self.endpoint = f"{proto}://{self.account}.blob.{suffix}"

    # -- signing -------------------------------------------------------

    def _canonicalized_resource(self, path: str, query: Dict[str, str]) -> str:
        res = f"/{self.account}{path}"
        for k in sorted(query):
            res += f"\n{k.lower()}:{query[k]}"
        return res

    def _sign(self, verb: str, path: str, query: Dict[str, str],
              headers: Dict[str, str], content_length: int) -> str:
        cl = str(content_length) if content_length else ""
        ms_headers = sorted(
            (k.lower(), v) for k, v in headers.items() if k.lower().startswith("x-ms-")
        )
        canon_headers = "".join(f"{k}:{v}\n" for k, v in ms_headers)
        string_to_sign = "\n".join(
            [
                verb,
                headers.get("Content-Encoding", ""),
                headers.get("Content-Language", ""),
                cl,
                headers.get("Content-MD5", ""),
                headers.get("Content-Type", ""),
                "",  # Date (we send x-ms-date instead)
                headers.get("If-Modified-Since", ""),
                headers.get("If-Match", ""),
                headers.get("If-None-Match", ""),
                headers.get("If-Unmodified-Since", ""),
                headers.get("Range", ""),
            ]
        ) + "\n" + canon_headers + self._canonicalized_resource(path, query)
        sig = base64.b64encode(
            hmac.new(self.key, string_to_sign.encode("utf-8"), hashlib.sha256).digest()
        ).decode()
        return f"SharedKey {self.account}:{sig}"

    def _open(
        self,
        verb: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        timeout: int = 60,
    ):
        """Signed request returning the open response (caller closes).
        Raises urllib.error.HTTPError on non-2xx."""
        query = dict(query or {})
        headers = dict(headers or {})
        headers["x-ms-date"] = email.utils.formatdate(usegmt=True)
        headers["x-ms-version"] = _API_VERSION
        # Sign the percent-encoded path — Azure canonicalizes the request
        # URL's encoded form, so signing the raw path 403s on names needing
        # escaping (spaces etc).
        qpath = urllib.parse.quote(path)
        if self.key:
            headers["Authorization"] = self._sign(
                verb, qpath, query, headers, len(body) if body else 0
            )
        qs = urllib.parse.urlencode(query)
        url = self.endpoint + qpath + ("?" + qs if qs else "")
        req = urllib.request.Request(url, data=body, method=verb, headers=headers)
        return urllib.request.urlopen(req, timeout=timeout)

    def _request(
        self,
        verb: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, bytes]:
        try:
            with self._open(verb, path, query, body, headers) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    # -- blob ops ------------------------------------------------------

    # Single-put limit is far higher, but chunking keeps peak memory bounded
    # for multi-GB checkpoint shards (one block in flight at a time).
    BLOCK_SIZE = 64 * 1024 * 1024

    def put_blob_from_file(self, container: str, name: str, path: str) -> None:
        """Upload a file; large files go through Put Block / Put Block List
        so at most one BLOCK_SIZE chunk is in memory."""
        size = os.path.getsize(path)
        if size <= self.BLOCK_SIZE:
            with open(path, "rb") as fh:
                self.put_blob(container, name, fh.read())
            return
        block_ids: List[str] = []
        with open(path, "rb") as fh:
            idx = 0
            while True:
                chunk = fh.read(self.BLOCK_SIZE)
                if not chunk:
                    break
                block_id = base64.b64encode(f"block-{idx:08d}".encode()).decode()
                status, body = self._request(
                    "PUT",
                    f"/{container}/{name}",
                    query={"comp": "block", "blockid": block_id},
                    body=chunk,
                    headers={"Content-Type": "application/octet-stream"},
                )
                if status not in (200, 201):
                    raise RuntimeError(
                        f"azure put block {name}#{idx}: HTTP {status}: {body[:200]!r}"
                    )
                block_ids.append(block_id)
                idx += 1
        xml_body = (
            "<?xml version='1.0' encoding='utf-8'?><BlockList>"
            + "".join(f"<Latest>{b}</Latest>" for b in block_ids)
            + "</BlockList>"
        ).encode()
        status, body = self._request(
            "PUT",
            f"/{container}/{name}",
            query={"comp": "blocklist"},
            body=xml_body,
            headers={"Content-Type": "application/xml"},
        )
        if status not in (200, 201):
            raise RuntimeError(f"azure put blocklist {name}: HTTP {status}: {body[:200]!r}")

    def get_blob_to_file(self, container: str, name: str, out_path: str) -> None:
        """Download a blob, streaming to disk in 1 MiB chunks."""
        try:
            with self._open(
                "GET", f"/{container}/{name}", timeout=300
            ) as resp, open(out_path, "wb") as fh:
                while True:
                    chunk = resp.read(1024 * 1024)
                    if not chunk:
                        break
                    fh.write(chunk)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(f"azure blob {container}/{name}") from e
            raise RuntimeError(f"azure get {name}: HTTP {e.code}") from e

    def put_blob(self, container: str, name: str, data: bytes) -> None:
        status, body = self._request(
            "PUT",
            f"/{container}/{name}",
            body=data,
            headers={"x-ms-blob-type": "BlockBlob",
                     "Content-Type": "application/octet-stream"},
        )
        if status not in (200, 201):
            raise RuntimeError(f"azure put {name}: HTTP {status}: {body[:200]!r}")

    def get_blob(self, container: str, name: str) -> bytes:
        status, body = self._request("GET", f"/{container}/{name}")
        if status == 404:
            raise FileNotFoundError(f"azure blob {container}/{name}")
        if status != 200:
            raise RuntimeError(f"azure get {name}: HTTP {status}: {body[:200]!r}")
        return body

    def delete_blob(self, container: str, name: str) -> None:
        status, body = self._request("DELETE", f"/{container}/{name}")
        if status not in (200, 202, 404):
            raise RuntimeError(f"azure delete {name}: HTTP {status}: {body[:200]!r}")

    def list_blobs(self, container: str, prefix: str = "") -> List[Tuple[str, int]]:
        """Return [(name, size)] under prefix, following continuation markers."""
        out: List[Tuple[str, int]] = []
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list", "prefix": prefix}
            if marker:
                query["marker"] = marker
            status, body = self._request("GET", f"/{container}", query=query)
            if status != 200:
                raise RuntimeError(f"azure list: HTTP {status}: {body[:200]!r}")
            root = ET.fromstring(body)
            for blob in root.iter("Blob"):
                name_el = blob.find("Name")
                size_el = blob.find(".//Content-Length")
                if name_el is not None and name_el.text:
                    size = int(size_el.text) if (size_el is not None and size_el.text) else 0
                    out.append((name_el.text, size))
            nm = root.find("NextMarker")
            marker = nm.text if (nm is not None and nm.text) else ""
            if not marker:
                return out
