"""Storage manager ABC + filesystem backends.

Reference: harness/determined/common/storage/base.py (StorageManager),
shared.py (shared_fs), directory.py. Cloud backends live in cloud.py.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import uuid
from typing import Any, Dict, Iterator, List, Optional


def iter_upload_files(src: str, paths: Optional[List[str]] = None):
    """Yield (local_path, rel_key) for every file upload() pushes from src.

    Shared by the cloud upload walks and the sharded-checkpoint resource
    reporting so the registry's file list always matches what was uploaded.
    """
    names = paths if paths is not None else os.listdir(src)
    for name in names:
        full = os.path.join(src, name)
        if os.path.isdir(full):
            for root, _, files in os.walk(full):
                for f in files:
                    p = os.path.join(root, f)
                    yield p, os.path.relpath(p, src)
        else:
            yield full, name


class StorageManager:
    """Checkpoints are directories keyed by UUID under a storage root."""

    def __init__(self, base_path: str):
        self.base_path = os.path.abspath(base_path)

    # -- core API ------------------------------------------------------

    def new_storage_id(self) -> str:
        return str(uuid.uuid4())

    def path_for(self, storage_id: str) -> str:
        return os.path.join(self.base_path, storage_id)

    @contextlib.contextmanager
    def store_path(self, storage_id: Optional[str] = None) -> Iterator[tuple]:
        """Yield (storage_id, writable_dir); commit on exit.

        Filesystem backends write in place — the TPU-critical property is
        that orbax/tensorstore can stream sharded arrays straight to the
        final location with no staging copy.
        """
        storage_id = storage_id or self.new_storage_id()
        path = self.path_for(storage_id)
        os.makedirs(path, exist_ok=True)
        yield storage_id, path

    @contextlib.contextmanager
    def restore_path(self, storage_id: str) -> Iterator[str]:
        path = self.path_for(storage_id)
        if not os.path.isdir(path):
            raise FileNotFoundError(f"checkpoint {storage_id} not found at {path}")
        yield path

    def delete(self, storage_id: str, globs: Optional[List[str]] = None) -> Dict[str, Any]:
        """Delete a checkpoint (or matching files). Returns remaining resources."""
        import glob as globlib

        path = self.path_for(storage_id)
        if not os.path.isdir(path):
            return {}
        if globs:
            for g in globs:
                for f in globlib.glob(os.path.join(path, g), recursive=True):
                    if os.path.isdir(f):
                        shutil.rmtree(f, ignore_errors=True)
                    else:
                        with contextlib.suppress(OSError):
                            os.unlink(f)
            if not os.listdir(path):
                shutil.rmtree(path, ignore_errors=True)
                return {}
            return self.list_files(storage_id)
        shutil.rmtree(path, ignore_errors=True)
        return {}

    def list_files(self, storage_id: str) -> Dict[str, int]:
        path = self.path_for(storage_id)
        out: Dict[str, int] = {}
        for root, _, files in os.walk(path):
            for f in files:
                full = os.path.join(root, f)
                out[os.path.relpath(full, path)] = os.path.getsize(full)
        return out

    # upload/download between a local working dir and storage ----------

    def upload(self, src: str, storage_id: str, paths: Optional[List[str]] = None) -> None:
        dst = self.path_for(storage_id)
        os.makedirs(dst, exist_ok=True)
        names = paths if paths is not None else os.listdir(src)
        for name in names:
            s, d = os.path.join(src, name), os.path.join(dst, name)
            os.makedirs(os.path.dirname(d), exist_ok=True)
            if os.path.isdir(s):
                shutil.copytree(s, d, dirs_exist_ok=True)
            else:
                shutil.copy2(s, d)

    def download(self, storage_id: str, dst: str, selector=None) -> None:
        src = self.path_for(storage_id)
        os.makedirs(dst, exist_ok=True)
        for rel in self.list_files(storage_id):
            if selector is not None and not selector(rel):
                continue
            s, d = os.path.join(src, rel), os.path.join(dst, rel)
            os.makedirs(os.path.dirname(d), exist_ok=True)
            shutil.copy2(s, d)


class SharedFSStorageManager(StorageManager):
    """`shared_fs`: a path visible to all hosts (NFS / gcsfuse on TPU-VMs)."""


class DirectoryStorageManager(StorageManager):
    """`directory`: a container-local path (persisted by bind-mount)."""


def from_config(config: Optional[Dict[str, Any]], default_base: str = "/tmp/determined_tpu/checkpoints") -> StorageManager:
    """Build a manager from an expconf `checkpoint_storage` block."""
    config = dict(config or {"type": "shared_fs", "host_path": default_base})
    stype = config.get("type", "shared_fs")
    if stype == "shared_fs":
        base = config.get("host_path", default_base)
        if config.get("storage_path"):
            base = os.path.join(base, config["storage_path"])
        return SharedFSStorageManager(base)
    if stype == "directory":
        return DirectoryStorageManager(config.get("container_path", default_base))
    if stype in ("gcs", "s3", "azure"):
        from determined_tpu.storage.cloud import cloud_from_config

        return cloud_from_config(stype, config)
    raise ValueError(f"unknown checkpoint storage type {stype!r}")
