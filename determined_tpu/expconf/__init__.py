"""expconf — the experiment-config schema system.

Reference: the JSON-schema-driven expconf machinery
(schemas/expconf/v0/*.json code-genned into master/pkg/schemas/expconf/,
~11.5k LoC; SURVEY.md §5 "Config/flag system"): validation, defaulting,
cluster-default merging and legacy shims. Here the same three operations are
implemented directly over dicts — `validate`, `apply_defaults`, `merge` —
and run client-side before submit; the master re-checks the load-bearing
invariants (searcher + entrypoint present).

Searcher variants mirror schemas/expconf/v0/searcher.json:16-51: single,
random, grid, async_halving, adaptive_asha (+ legacy aliases adaptive,
adaptive_simple, sync_halving).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

SEARCHER_NAMES = {
    "single",
    "random",
    "grid",
    "async_halving",
    "adaptive_asha",
    # legacy aliases (reference legacy.go shims)
    "adaptive",
    "adaptive_simple",
    "sync_halving",
    "custom",
}

HPARAM_TYPES = {"const", "int", "double", "log", "categorical"}

STORAGE_TYPES = {"shared_fs", "directory", "gcs", "s3", "azure"}


def _is_hparam_spec(v: Any) -> bool:
    return isinstance(v, dict) and isinstance(v.get("type"), str)


def _validate_hparam(name: str, spec: Any, errors: List[str]) -> None:
    if not isinstance(spec, dict):
        return  # bare value == const
    t = spec.get("type")
    if t is None:
        # nested hparam group
        for k, v in spec.items():
            _validate_hparam(f"{name}.{k}", v, errors)
        return
    if t not in HPARAM_TYPES:
        errors.append(f"hyperparameters.{name}: unknown type {t!r}")
        return
    if t == "const" and "val" not in spec:
        errors.append(f"hyperparameters.{name}: const requires `val`")
    if t == "categorical" and not spec.get("vals"):
        errors.append(f"hyperparameters.{name}: categorical requires `vals`")
    if t in ("int", "double", "log"):
        for field in ("minval", "maxval"):
            if field not in spec:
                errors.append(f"hyperparameters.{name}: {t} requires `{field}`")
        if "minval" in spec and "maxval" in spec and spec["minval"] > spec["maxval"]:
            errors.append(f"hyperparameters.{name}: minval > maxval")


def _validate_mesh(mesh: Any, resources: Dict[str, Any], errors: List[str]) -> None:
    """`hyperparameters.mesh` is THE home of the allocation's mesh request
    (determined_tpu/parallel/mesh.py MeshConfig): axis name → size, -1 means
    "absorb the remaining chips" (at most one axis), product must match
    resources.slots_per_trial when fully specified."""
    if mesh is None:
        return
    from determined_tpu.parallel.mesh import AXIS_ORDER

    if not isinstance(mesh, dict):
        errors.append("hyperparameters.mesh must be a mapping of axis -> size")
        return
    unknown = sorted(set(mesh) - set(AXIS_ORDER))
    if unknown:
        errors.append(
            f"hyperparameters.mesh: unknown axes {unknown}; valid: {list(AXIS_ORDER)}"
        )
    sizes = []
    n_unknown = 0
    for k, v in mesh.items():
        if isinstance(v, bool) or not isinstance(v, int) or v == 0 or v < -1:
            errors.append(
                f"hyperparameters.mesh.{k}: size must be a positive int or -1"
            )
            return
        if v == -1:
            n_unknown += 1
        else:
            sizes.append(v)
    # MeshConfig defaults an omitted `data` axis to -1 (absorb remaining
    # chips) — mirror that here so runtime-valid configs pass validation.
    if "data" not in mesh:
        n_unknown += 1
    if n_unknown > 1:
        errors.append("hyperparameters.mesh: at most one axis may be -1")
    # apply_defaults will set slots_per_trial=1 — validate against that same
    # default so a mesh asking for 8 chips with no resources block fails at
    # submit time, not at MeshConfig.resolve() mid-launch.
    slots = resources.get("slots_per_trial", 1)
    if isinstance(slots, int) and slots > 0 and not unknown:
        import math

        product = math.prod(sizes)
        if n_unknown == 0 and product != slots:
            errors.append(
                f"hyperparameters.mesh: axis product {product} != "
                f"resources.slots_per_trial {slots}"
            )
        elif n_unknown == 1 and slots % product != 0:
            errors.append(
                f"hyperparameters.mesh: slots_per_trial {slots} not divisible "
                f"by fixed axes product {product}"
            )


def _length_units(v: Any) -> Optional[int]:
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, dict):
        for unit in ("batches", "records", "epochs"):
            if unit in v:
                return int(v[unit])
    return None


def validate(config: Dict[str, Any]) -> List[str]:
    """Return a list of human-readable schema errors (empty = valid)."""
    errors: List[str] = []
    if not isinstance(config, dict):
        return ["config must be a mapping"]

    serving = config.get("serving")
    if serving is not None:
        _validate_serving(serving, errors)

    # Serving configs describe a deployment, not a training loop: the
    # entrypoint defaults to the serve task and there is no searcher.
    if not config.get("entrypoint") and serving is None:
        errors.append("entrypoint is required")

    searcher = config.get("searcher")
    if not isinstance(searcher, dict):
        if serving is None:
            errors.append("searcher is required")
    else:
        name = searcher.get("name")
        if name not in SEARCHER_NAMES:
            errors.append(f"searcher.name must be one of {sorted(SEARCHER_NAMES)}")
        if name != "custom":
            if not searcher.get("metric"):
                errors.append("searcher.metric is required")
            if _length_units(searcher.get("max_length")) in (None, 0):
                errors.append("searcher.max_length is required (batches)")
        if name == "random" and not searcher.get("max_trials"):
            errors.append("searcher.max_trials is required for random search")
        if name in ("async_halving", "sync_halving"):
            if not searcher.get("num_rungs"):
                errors.append("searcher.num_rungs is required for async_halving")
        if name in ("adaptive_asha", "adaptive", "adaptive_simple"):
            if not searcher.get("max_trials"):
                errors.append("searcher.max_trials is required for adaptive_asha")
        divisor = searcher.get("divisor")
        if divisor is not None and divisor <= 1:
            errors.append("searcher.divisor must be > 1")

    hparams = config.get("hyperparameters", {})
    if not isinstance(hparams, dict):
        errors.append("hyperparameters must be a mapping")
    else:
        for k, v in hparams.items():
            if k == "mesh":
                continue  # the mesh block is not an hparam search space
            _validate_hparam(k, v, errors)
        _validate_mesh(
            hparams.get("mesh"),
            config.get("resources", {}) if isinstance(config.get("resources"), dict)
            else {},
            errors,
        )
        if isinstance(searcher, dict) and searcher.get("name") == "grid":
            def needs_count(spec: Any) -> bool:
                if not _is_hparam_spec(spec):
                    if isinstance(spec, dict):
                        return any(needs_count(v) for v in spec.values())
                    return False
                return spec["type"] in ("int", "double", "log") and not spec.get("count")

            for k, v in hparams.items():
                if needs_count(v):
                    errors.append(
                        f"hyperparameters.{k}: grid search requires `count` on numeric ranges"
                    )

    res = config.get("resources", {})
    if not isinstance(res, dict):
        errors.append("resources must be a mapping")
    else:
        spt = res.get("slots_per_trial", 1)
        if not isinstance(spt, int) or spt < 0:
            errors.append("resources.slots_per_trial must be a non-negative int")
        _validate_elastic(res.get("elastic"), res, errors)

    storage = config.get("checkpoint_storage")
    if storage is not None:
        if not isinstance(storage, dict) or storage.get("type") not in STORAGE_TYPES:
            errors.append(
                f"checkpoint_storage.type must be one of {sorted(STORAGE_TYPES)}"
            )
        elif storage["type"] in ("gcs", "s3") and not storage.get("bucket"):
            errors.append("checkpoint_storage.bucket is required for cloud storage")
        elif storage["type"] == "azure" and not storage.get("container"):
            errors.append("checkpoint_storage.container is required for azure storage")

    mr = config.get("max_restarts")
    if mr is not None and (not isinstance(mr, int) or mr < 0):
        errors.append("max_restarts must be a non-negative int")

    _validate_registry(config.get("registry"), serving, errors)
    _validate_environment(config.get("environment"), errors)
    _validate_log_policies(config.get("log_policies"), errors)
    _validate_preflight(config.get("preflight"), errors)
    _validate_prefetch(config.get("prefetch"), errors)
    _validate_health(config.get("health"), errors)
    _validate_preemption(config.get("preemption"), errors)
    _validate_compile(config.get("compile"), errors)
    _validate_optimizations(config.get("optimizations"), errors)

    return errors


# The TPU meaning of the `optimizations:` block (the torch-era keys —
# aggregation_frequency etc. — are shimmed away; see shim()).
OPTIMIZATION_KEYS = ("attention_impl", "attention_bf16",
                     "overlap_allgather", "prepartition_inputs")
ATTENTION_IMPLS = ("auto", "pallas", "reference", "dense")


def _validate_optimizations(block: Any, errors: List[str]) -> None:
    """`optimizations:` — training-step performance knobs
    (docs/training-perf.md): attention kernel selection, the bf16
    attention path, the one-layer-ahead fsdp all-gather overlap, and
    pre-partitioned step inputs."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("optimizations must be a mapping")
        return
    unknown = sorted(set(block) - set(OPTIMIZATION_KEYS))
    if unknown:
        errors.append(
            f"optimizations: unknown keys {unknown}; valid: "
            f"{', '.join(OPTIMIZATION_KEYS)}")
    impl = block.get("attention_impl")
    if impl is not None and impl not in ATTENTION_IMPLS:
        errors.append(
            f"optimizations.attention_impl {impl!r} must be one of "
            f"{'|'.join(ATTENTION_IMPLS)}")
    for flag in ("attention_bf16", "overlap_allgather",
                 "prepartition_inputs"):
        if flag in block and not isinstance(block[flag], bool):
            errors.append(f"optimizations.{flag} must be a bool")


def _validate_compile(block: Any, errors: List[str]) -> None:
    """`compile:` — the compile farm (docs/compile-farm.md): artifact
    exchange (on by default), background AOT precompilation while trials
    queue (opt-in), and batch-size bucketing so sweeps share executables."""
    if block is None:
        return
    if isinstance(block, bool):
        return  # bare bool == enabled switch
    if not isinstance(block, dict):
        errors.append("compile must be a bool or a mapping")
        return
    valid = {"enabled", "background", "bucket_batch_sizes", "buckets",
             "max_executables", "upload"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"compile: unknown keys {unknown}; valid: {sorted(valid)}")
    for flag in ("enabled", "background", "bucket_batch_sizes", "upload"):
        if flag in block and not isinstance(block[flag], bool):
            errors.append(f"compile.{flag} must be a bool")
    me = block.get("max_executables")
    if me is not None and (
        isinstance(me, bool) or not isinstance(me, int) or me < 1
    ):
        errors.append("compile.max_executables must be a positive int")
    buckets = block.get("buckets")
    if buckets is not None:
        if not isinstance(buckets, list) or not buckets or any(
            isinstance(b, bool) or not isinstance(b, int) or b < 1
            for b in buckets
        ):
            errors.append(
                "compile.buckets must be a non-empty list of positive ints")


def _validate_preemption(block: Any, errors: List[str]) -> None:
    """`preemption:` — spot-survival knobs (docs/checkpointing.md): the
    deadline-budgeted emergency checkpoint a trial takes when its node
    receives an infrastructure termination notice."""
    if block is None:
        return
    if isinstance(block, bool):
        return  # bare bool == emergency_checkpoint switch
    if not isinstance(block, dict):
        errors.append("preemption must be a bool or a mapping")
        return
    valid = {"emergency_checkpoint", "budget_safety_factor",
             "budget_margin_sec"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"preemption: unknown keys {unknown}; valid: {sorted(valid)}")
    ec = block.get("emergency_checkpoint")
    if ec is not None and not isinstance(ec, bool):
        errors.append("preemption.emergency_checkpoint must be a bool")
    v = block.get("budget_safety_factor")
    if v is not None and (
        isinstance(v, bool) or not isinstance(v, (int, float)) or v < 1
    ):
        errors.append("preemption.budget_safety_factor must be a number >= 1")
    v = block.get("budget_margin_sec")
    if v is not None and (
        isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0
    ):
        errors.append(
            "preemption.budget_margin_sec must be a non-negative number")


def _validate_elastic(block: Any, resources: Dict[str, Any],
                      errors: List[str]) -> None:
    """`resources.elastic:` — elastic re-meshing bounds (docs/elasticity.md).

    An elastic trial's allocation size is a scheduler decision inside
    [min_slots, max_slots]; `slots_per_trial` is the PREFERRED size. On
    capacity loss the scheduler offers a shrink instead of a requeue; on
    idle capacity it grows the trial back (resharding state through the
    declared PartitionSpecs either way)."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("resources.elastic must be a mapping")
        return
    valid = {"min_slots", "max_slots"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"resources.elastic: unknown keys {unknown}; valid: "
            f"{sorted(valid)}")
    for key in valid:
        v = block.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, int) or v < 1
        ):
            errors.append(f"resources.elastic.{key} must be a positive int")
            return
    mn = block.get("min_slots", 1)
    spt = resources.get("slots_per_trial", 1)
    mx = block.get("max_slots", spt if isinstance(spt, int) else None)
    if isinstance(mn, int) and isinstance(mx, int) and mn > mx:
        errors.append("resources.elastic.min_slots > max_slots")
        return
    if isinstance(spt, int) and spt > 0:
        if isinstance(mn, int) and spt < mn:
            errors.append(
                "resources.slots_per_trial (the preferred size) is below "
                "resources.elastic.min_slots")
        if isinstance(mx, int) and spt > mx:
            errors.append(
                "resources.slots_per_trial (the preferred size) exceeds "
                "resources.elastic.max_slots")


def _validate_health(block: Any, errors: List[str]) -> None:
    """`health:` — the self-healing loop (docs/checkpointing.md): the
    divergence sentinel's on_nan policy and the step watchdog timeout."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("health must be a mapping")
        return
    valid = {"on_nan", "rollback_window", "max_rollbacks", "step_timeout_sec"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"health: unknown keys {unknown}; valid: {sorted(valid)}")
    on_nan = block.get("on_nan")
    if on_nan is not None and on_nan not in ("warn", "rollback", "fail"):
        errors.append("health.on_nan must be one of warn|rollback|fail")
    for key in ("rollback_window", "max_rollbacks"):
        v = block.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, int) or v < 0
        ):
            errors.append(f"health.{key} must be a non-negative int")
    if block.get("max_rollbacks") == 0:
        errors.append("health.max_rollbacks must be >= 1")
    v = block.get("step_timeout_sec")
    if v is not None and (
        isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0
    ):
        errors.append("health.step_timeout_sec must be a non-negative "
                      "number (0 disables the watchdog)")


def _validate_registry(block: Any, serving: Any,
                       errors: List[str]) -> None:
    """`registry:` — train→serve auto-promotion (docs/serving.md "Model
    lifecycle"): when the experiment COMPLETES, the master registers its
    winning checkpoint as the next version of `model` — the searcher-best
    validation checkpoint (`promote: best`, the default) or the newest
    COMPLETED one (`promote: latest`)."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("registry must be a mapping")
        return
    if serving is not None:
        errors.append(
            "registry: promotion belongs to training configs — a serving "
            "config consumes registered versions, it does not produce "
            "them")
    valid = {"model", "promote"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"registry: unknown keys {unknown}; valid: {sorted(valid)}")
    model = block.get("model")
    if not isinstance(model, str) or not model:
        errors.append("registry.model must be a non-empty model name")
    elif ":" in model:
        errors.append(
            "registry.model must be a bare model name (the registry "
            "assigns the version number)")
    promote = block.get("promote")
    if promote is not None and promote not in ("best", "latest"):
        errors.append("registry.promote must be one of: best, latest")


def _validate_serving(block: Any, errors: List[str]) -> None:
    """`serving:` — a `det serve` deployment (docs/serving.md): which
    checkpoint to load, the model family/config to rebuild it into, and
    the continuous-batcher capacity knobs."""
    if not isinstance(block, dict):
        errors.append("serving must be a mapping")
        return
    valid = {"checkpoint", "trial_id", "model", "model_config",
             "max_batch_size", "max_seq_len", "kv_block_size",
             "kv_num_blocks", "prefix_cache", "attention_impl",
             "prefill_buckets", "queue_depth", "port", "seed",
             "stats_log_period_s", "replicas", "heartbeat_period_s",
             "trace_sample", "slo_ms", "warm_aot", "adapters", "canary",
             "model_version"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"serving: unknown keys {unknown}; valid: {sorted(valid)}")
    ckpt = block.get("checkpoint")
    if ckpt is not None and not isinstance(ckpt, str):
        errors.append(
            "serving.checkpoint must be a checkpoint storage id or "
            "'latest'")
    model = block.get("model")
    if model is not None and model not in ("gpt2",):
        errors.append("serving.model must be one of: gpt2")
    mc = block.get("model_config")
    if mc is not None and not isinstance(mc, dict):
        errors.append("serving.model_config must be a mapping")
    for key in ("max_batch_size", "max_seq_len", "kv_block_size",
                "kv_num_blocks", "queue_depth"):
        v = block.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, int) or v < 1
        ):
            errors.append(f"serving.{key} must be a positive int")
    pc = block.get("prefix_cache")
    if pc is not None and not isinstance(pc, bool):
        errors.append("serving.prefix_cache must be a boolean")
    wa = block.get("warm_aot")
    if wa is not None and not isinstance(wa, bool):
        errors.append("serving.warm_aot must be a boolean")
    impl = block.get("attention_impl")
    if impl is not None and impl not in ("auto", "pallas", "reference",
                                         "dense"):
        errors.append(
            "serving.attention_impl must be one of: auto, pallas, "
            "reference, dense")
    for key in ("trial_id", "port", "seed"):
        v = block.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, int) or v < 0
        ):
            errors.append(f"serving.{key} must be a non-negative int")
    buckets = block.get("prefill_buckets")
    if buckets is not None:
        if (not isinstance(buckets, list) or not buckets or any(
                isinstance(b, bool) or not isinstance(b, int) or b < 1
                for b in buckets)):
            errors.append(
                "serving.prefill_buckets must be a non-empty list of "
                "positive ints")
        elif sorted(buckets) != buckets:
            errors.append("serving.prefill_buckets must be ascending")
    hb = block.get("heartbeat_period_s")
    if hb is not None and (
        isinstance(hb, bool) or not isinstance(hb, (int, float)) or hb <= 0
    ):
        errors.append("serving.heartbeat_period_s must be a positive number")
    # Request-path observability (docs/serving.md "Request latency &
    # SLOs"): span sampling fraction + the latency SLO that arms the
    # always-trace-slow path and the master's slow-request ring.
    ts = block.get("trace_sample")
    if ts is not None and (
        isinstance(ts, bool) or not isinstance(ts, (int, float))
        or not 0 <= ts <= 1
    ):
        errors.append("serving.trace_sample must be a number in [0, 1]")
    slo = block.get("slo_ms")
    if slo is not None and (
        isinstance(slo, bool) or not isinstance(slo, (int, float))
        or slo <= 0
    ):
        errors.append("serving.slo_ms must be a positive number")
    mv = block.get("model_version")
    if mv is not None and (not isinstance(mv, str) or not mv):
        errors.append(
            "serving.model_version must be a registry label "
            "('<model>' or '<model>:<version>')")
    _validate_serving_adapters(block.get("adapters"), errors)
    _validate_serving_canary(block.get("canary"), errors)
    _validate_serving_replicas(block.get("replicas"), errors)


def _validate_serving_adapters(adapters: Any, errors: List[str]) -> None:
    """`serving.adapters:` — multi-adapter replicas (docs/serving.md
    "Model lifecycle"): LoRA-style head-delta fine-tunes resident beside
    one base executable, routed per request by `model:` name. Each entry
    names an adapter and the committed checkpoint its weights come from."""
    if adapters is None:
        return
    if not isinstance(adapters, list):
        errors.append(
            "serving.adapters must be a list of {name, checkpoint}")
        return
    seen = set()
    for i, a in enumerate(adapters):
        if not isinstance(a, dict):
            errors.append(
                f"serving.adapters[{i}] must be a mapping with "
                "`name` and `checkpoint`")
            continue
        unknown = sorted(set(a) - {"name", "checkpoint"})
        if unknown:
            errors.append(
                f"serving.adapters[{i}]: unknown keys {unknown}; "
                "valid: name, checkpoint")
        name = a.get("name")
        if not isinstance(name, str) or not name:
            errors.append(
                f"serving.adapters[{i}].name must be a non-empty string")
        elif name in seen:
            # Duplicate names would make per-request `model:` routing
            # ambiguous — which fine-tune did the caller mean?
            errors.append(
                f"serving.adapters[{i}].name {name!r} is a duplicate "
                "(adapter names route requests and must be unique)")
        elif name == "base":
            errors.append(
                "serving.adapters: the name 'base' is reserved for the "
                "deployment's base checkpoint")
        else:
            seen.add(name)
        ck = a.get("checkpoint")
        if not isinstance(ck, str) or not ck:
            errors.append(
                f"serving.adapters[{i}].checkpoint must be a checkpoint "
                "storage id")


def _validate_serving_canary(block: Any, errors: List[str]) -> None:
    """`serving.canary:` — a config-declared canary split (docs/serving.md
    "Model lifecycle"): the deployment starts with `fraction` of traced
    generations routed to `model:version` (or `checkpoint`) replicas.
    The fraction rule is mirrored as DTL208 in native preflight — the
    deployment-create gate enforces it master-side."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("serving.canary must be a mapping")
        return
    valid = {"model", "version", "checkpoint", "fraction", "replicas"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"serving.canary: unknown keys {unknown}; "
            f"valid: {sorted(valid)}")
    has_model = isinstance(block.get("model"), str) and block.get("model")
    has_ckpt = (isinstance(block.get("checkpoint"), str)
                and block.get("checkpoint"))
    if not has_model and not has_ckpt:
        errors.append(
            "serving.canary requires `model` (a registry name) or "
            "`checkpoint` (a storage id) naming the canary version")
    v = block.get("version")
    if v is not None and (
        isinstance(v, bool) or not isinstance(v, int) or v < 1
    ):
        errors.append(
            "serving.canary.version must be a positive int "
            "(a registered model version number)")
    if v is not None and not has_model:
        errors.append(
            "serving.canary.version requires `model` (versions are "
            "registry coordinates, not checkpoint ids)")
    frac = block.get("fraction")
    if frac is not None and (
        isinstance(frac, bool) or not isinstance(frac, (int, float))
        or not 0 < frac < 1
    ):
        errors.append(
            "serving.canary.fraction must be strictly inside (0, 1) "
            "(DTL208): 0 routes nothing, 1 is a rolling update")
    reps = block.get("replicas")
    if reps is not None and (
        isinstance(reps, bool) or not isinstance(reps, int) or reps < 1
    ):
        errors.append("serving.canary.replicas must be a positive int")


def _validate_serving_replicas(block: Any, errors: List[str]) -> None:
    """`serving.replicas:` — a deployment (docs/serving.md "Deployments &
    autoscaling"): the master keeps `target` replicas within [min, max],
    and the autoscaler moves target from sustained backpressure / idle
    cooldown when min < max. `min: 0` enables scale-to-zero: an idle
    deployment drains its last replica, and the router's demand wake
    respawns one within `cold_start_budget_s`. `on_demand_floor` replicas
    (default: min) avoid preemptible agents; everything above the floor
    is reclaimable spot surplus."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("serving.replicas must be a mapping")
        return
    valid = {"min", "max", "target", "scale_up_after_s",
             "scale_down_after_s", "scale_up_threshold",
             "scale_down_threshold", "on_demand_floor",
             "cold_start_budget_s"}
    unknown = sorted(set(block) - valid)
    if unknown:
        errors.append(
            f"serving.replicas: unknown keys {unknown}; "
            f"valid: {sorted(valid)}")
    counts = {}
    for key in ("min", "max", "target"):
        v = block.get(key)
        if v is None:
            continue
        if key == "max":
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                errors.append(
                    f"serving.replicas.{key} must be a positive int")
            else:
                counts[key] = v
        elif isinstance(v, bool) or not isinstance(v, int) or v < 0:
            # min: 0 (and target: 0 with it) is scale-to-zero, legal.
            errors.append(
                f"serving.replicas.{key} must be a non-negative int")
        else:
            counts[key] = v
    lo = counts.get("min", 1)
    hi = counts.get("max", max(lo, counts.get("target", lo), 1))
    target = counts.get("target", lo)
    if "min" in counts and "max" in counts and lo > hi:
        errors.append("serving.replicas.min must be <= max")
    elif not (lo <= target <= hi):
        errors.append(
            "serving.replicas.target must be within [min, max]")
    floor = block.get("on_demand_floor")
    if floor is not None:
        if isinstance(floor, bool) or not isinstance(floor, int) or floor < 0:
            errors.append(
                "serving.replicas.on_demand_floor must be a non-negative "
                "int")
        elif "max" in counts and floor > counts["max"]:
            errors.append(
                "serving.replicas.on_demand_floor must be <= max (a floor "
                "above max can never be satisfied)")
    budget = block.get("cold_start_budget_s")
    if budget is not None and (
        isinstance(budget, bool) or not isinstance(budget, (int, float))
        or budget <= 0
    ):
        errors.append(
            "serving.replicas.cold_start_budget_s must be a positive "
            "number")
    for key in ("scale_up_after_s", "scale_down_after_s"):
        v = block.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0
        ):
            errors.append(
                f"serving.replicas.{key} must be a non-negative number")
    for key in ("scale_up_threshold", "scale_down_threshold"):
        v = block.get(key)
        if v is not None and (
            isinstance(v, bool) or not isinstance(v, (int, float))
            or not 0 < v <= 2
        ):
            errors.append(
                f"serving.replicas.{key} must be in (0, 2] (queue "
                "fraction + batch occupancy)")


def _validate_prefetch(block: Any, errors: List[str]) -> None:
    """`prefetch:` — the async input pipeline (determined_tpu/data): on by
    default; trials opt out or tune the queue depth here."""
    if block is None:
        return
    if isinstance(block, bool):
        return  # bare bool == enabled switch
    if not isinstance(block, dict):
        errors.append("prefetch must be a bool or a mapping")
        return
    unknown = sorted(set(block) - {"enabled", "depth", "shard"})
    if unknown:
        errors.append(
            f"prefetch: unknown keys {unknown}; valid: enabled, depth, shard")
    for flag in ("enabled", "shard"):
        if flag in block and not isinstance(block[flag], bool):
            errors.append(f"prefetch.{flag} must be a bool")
    depth = block.get("depth")
    if depth is not None and (
        isinstance(depth, bool) or not isinstance(depth, int) or depth < 1
    ):
        errors.append("prefetch.depth must be a positive int")


def _validate_preflight(block: Any, errors: List[str]) -> None:
    """`preflight:` — static-analyzer knobs (docs/preflight.md): the
    master-side create gate, config-level rule suppression, and the HBM
    budget that arms DTL004."""
    if block is None:
        return
    if not isinstance(block, dict):
        errors.append("preflight must be a mapping")
        return
    gate = block.get("gate")
    if gate is not None and gate not in ("error", "warn", "off"):
        errors.append("preflight.gate must be one of error|warn|off")
    suppress = block.get("suppress")
    if suppress is not None:
        import re as _re

        if not isinstance(suppress, list):
            errors.append("preflight.suppress must be a list of rule codes")
        else:
            for c in suppress:
                if not isinstance(c, str) or not _re.match(r"^DTL\d{3}$", c):
                    errors.append(
                        f"preflight.suppress entry {c!r} is not a DTLnnn "
                        "rule code")
    hbm = block.get("hbm_gb_per_device")
    if hbm is not None and (
        isinstance(hbm, bool) or not isinstance(hbm, (int, float)) or hbm <= 0
    ):
        errors.append("preflight.hbm_gb_per_device must be a positive number")


def cross_field_diagnostics(config: Dict[str, Any]):
    """The DTL2xx cross-field rules (batch/mesh divisibility, searcher
    budget vs ASHA rungs) as structured diagnostics rather than bare
    exceptions — the same set the native master enforces at experiment
    create (native/master/preflight.cc). Returns a list of
    analysis.Diagnostic."""
    from determined_tpu.analysis import config_rules

    return config_rules.check_config(shim(config))


def _validate_log_policies(policies: Any, errors: List[str]) -> None:
    """`log_policies:` — regex actions on task logs (reference
    logpattern.go + schemas/expconf/v0/log-policy.json):
    [{pattern: regex, action: {type: cancel_retries|exclude_node}}]."""
    if policies is None:
        return
    if not isinstance(policies, list):
        errors.append("log_policies must be a list")
        return
    import re as _re

    for i, p in enumerate(policies):
        if not isinstance(p, dict) or not isinstance(p.get("pattern"), str):
            errors.append(f"log_policies[{i}]: requires a `pattern` string")
            continue
        try:
            _re.compile(p["pattern"])
        except _re.error as e:
            errors.append(f"log_policies[{i}].pattern: invalid regex: {e}")
        else:
            # The master matches with ECMAScript std::regex: python-only
            # constructs (named groups, inline flags) would be silently
            # inert there — reject them at submit time. (?: (?= (?! are
            # fine in both dialects.
            if _re.search(r"\(\?(?![:=!])", p["pattern"]):
                errors.append(
                    f"log_policies[{i}].pattern: named groups / inline "
                    "flags are not supported by the master's regex engine"
                )
        action = p.get("action")
        atype = action.get("type") if isinstance(action, dict) else action
        if atype not in ("cancel_retries", "exclude_node"):
            errors.append(
                f"log_policies[{i}].action.type must be cancel_retries or "
                "exclude_node"
            )


def _validate_environment(envcfg: Any, errors: List[str]) -> None:
    """`environment:` block (reference task-spec env rendering,
    master/pkg/tasks/task.go:194-234): flat "K": "V" pairs and/or
    environment_variables ["K=V", ...], plus TPU-native `venv` (interpreter
    activation) and `python_path` (extra package roots)."""
    if envcfg is None:
        return
    if not isinstance(envcfg, dict):
        errors.append("environment must be a mapping")
        return
    ev = envcfg.get("environment_variables")
    if ev is not None:
        if not isinstance(ev, list):
            errors.append("environment.environment_variables must be a list")
        else:
            for kv in ev:
                if not isinstance(kv, str) or "=" not in kv:
                    errors.append(
                        f"environment.environment_variables entry {kv!r} "
                        "must be a 'KEY=value' string"
                    )
    venv = envcfg.get("venv")
    if venv is not None and not isinstance(venv, str):
        errors.append("environment.venv must be a path string")
    pp = envcfg.get("python_path")
    if pp is not None and (
        not isinstance(pp, list) or not all(isinstance(p, str) for p in pp)
    ):
        errors.append("environment.python_path must be a list of path strings")
    for k, v in envcfg.items():
        if k in ("environment_variables", "venv", "python_path"):
            continue
        if not isinstance(v, str):
            errors.append(
                f"environment.{k}: flat entries are env vars and must be "
                "strings"
            )


def shim(config: Dict[str, Any]) -> Dict[str, Any]:
    """Translate legacy config shapes into the current schema (reference
    pkg/schemas/expconf/legacy.go + the v0 version shims): configs written
    for older formats keep working, torch/container-era knobs that have no
    TPU meaning are dropped with a warning instead of failing validation.

    Shims (applied before validate):
      - bare-int lengths → {"batches": N}: searcher.max_length,
        min_validation_period, min_checkpoint_period
      - searcher.max_steps (ancient) → max_length {batches}
      - searcher.name "adaptive"/"adaptive_simple" → adaptive_asha,
        "sync_halving" → async_halving (semantics preserved; the legacy
        names stay accepted by validate for byte-for-byte old configs)
      - resources.slots → resources.slots_per_trial
      - optimizations: the torch-era keys (aggregation_frequency, ...)
        are dropped per-key with a warning; the TPU keys
        (attention_impl, attention_bf16, overlap_allgather,
        prepartition_inputs) are kept. A block left empty is dropped.
      - dropped with a warning: bind_mounts (no containers),
        data_layers, entrypoint_script
    """
    import warnings

    c = copy.deepcopy(config)
    if not isinstance(c, dict):
        return c

    searcher = c.get("searcher")
    if isinstance(searcher, dict):
        if "max_length" not in searcher and "max_steps" in searcher:
            searcher["max_length"] = {"batches": searcher.pop("max_steps")}
        if isinstance(searcher.get("max_length"), (int, float)):
            searcher["max_length"] = {"batches": int(searcher["max_length"])}
    for period in ("min_validation_period", "min_checkpoint_period"):
        if isinstance(c.get(period), (int, float)):
            c[period] = {"batches": int(c[period])}

    res = c.get("resources")
    if isinstance(res, dict) and "slots_per_trial" not in res and \
            isinstance(res.get("slots"), int):
        res["slots_per_trial"] = res.pop("slots")

    opt = c.get("optimizations")
    if isinstance(opt, dict):
        for legacy in sorted(set(opt) - set(OPTIMIZATION_KEYS)):
            warnings.warn(
                f"expconf: `optimizations.{legacy}` is a torch-era knob "
                "with no meaning on the TPU platform and is ignored",
                stacklevel=2)
            opt.pop(legacy)
        if not opt:
            c.pop("optimizations")
    elif "optimizations" in c:
        warnings.warn(
            "expconf: `optimizations` must be a mapping of TPU knobs "
            "(attention_impl, ...); the legacy form is ignored",
            stacklevel=2)
        c.pop("optimizations")

    for dropped in ("bind_mounts", "data_layers", "entrypoint_script"):
        if dropped in c:
            warnings.warn(
                f"expconf: `{dropped}` has no meaning on the TPU platform "
                "and is ignored", stacklevel=2)
            c.pop(dropped)
    return c


def apply_defaults(config: Dict[str, Any]) -> Dict[str, Any]:
    """Fill schema defaults (reference: WithDefaults code-gen)."""
    c = copy.deepcopy(config)
    c.setdefault("name", "unnamed-experiment")
    c.setdefault("description", "")
    c.setdefault("labels", [])
    c.setdefault("hyperparameters", {})
    c.setdefault("max_restarts", 5)
    c.setdefault("scheduling_unit", 100)
    c.setdefault("records_per_epoch", 0)
    c.setdefault("min_validation_period", {"batches": 0})
    c.setdefault("min_checkpoint_period", {"batches": 0})
    c.setdefault("perform_initial_validation", False)
    res = c.setdefault("resources", {})
    res.setdefault("slots_per_trial", 1)
    res.setdefault("resource_pool", "default")
    res.setdefault("priority", 42)
    if isinstance(res.get("elastic"), dict):
        el = res["elastic"]
        el.setdefault("min_slots", 1)
        el.setdefault("max_slots", res["slots_per_trial"])
    if isinstance(c.get("serving"), dict):
        s = c["serving"]
        s.setdefault("checkpoint", "latest")
        s.setdefault("model", "gpt2")
        s.setdefault("max_batch_size", 8)
        s.setdefault("max_seq_len", 256)
        s.setdefault("kv_block_size", 16)
        s.setdefault("prefix_cache", True)
        s.setdefault("attention_impl", "auto")
        s.setdefault("queue_depth", 64)
        if isinstance(s.get("replicas"), dict):
            rep = s["replicas"]
            rep.setdefault("min", 1)
            rep.setdefault("target", rep["min"])
            # max must stay >= 1 even under min: 0 (scale-to-zero).
            rep.setdefault("max", max(rep["min"], rep["target"], 1))
        if isinstance(s.get("canary"), dict):
            cb = s["canary"]
            cb.setdefault("fraction", 0.05)
            cb.setdefault("replicas", 1)
        # No searcher/validation machinery for a deployment config.
        return c
    if isinstance(c.get("registry"), dict):
        c["registry"].setdefault("promote", "best")
    searcher = c.setdefault("searcher", {})
    searcher.setdefault("smaller_is_better", True)
    name = searcher.get("name")
    if name in ("async_halving", "sync_halving", "adaptive_asha", "adaptive",
                "adaptive_simple"):
        searcher.setdefault("divisor", 4)
        searcher.setdefault("mode", "standard")
        if name in ("async_halving", "sync_halving"):
            searcher.setdefault("num_rungs", 5)
        else:
            searcher.setdefault("max_rungs", 5)
    if name in ("random", "adaptive_asha", "adaptive", "adaptive_simple",
                "async_halving"):
        mt = searcher.get("max_trials", 16)
        searcher.setdefault("max_trials", mt)
        searcher.setdefault("max_concurrent_trials", min(mt, 16))
    c.setdefault("reproducibility", {})
    c.setdefault("environment", {})
    c.setdefault("profiling", {"enabled": False})
    pf = c.setdefault("prefetch", {})
    if isinstance(pf, dict):
        pf.setdefault("enabled", True)
        pf.setdefault("depth", 2)
    cc = c.setdefault("compile", {})
    if isinstance(cc, dict):
        cc.setdefault("enabled", True)
        cc.setdefault("background", False)
        cc.setdefault("bucket_batch_sizes", False)
        cc.setdefault("max_executables", 8)
        cc.setdefault("upload", True)
    opt = c.setdefault("optimizations", {})
    if isinstance(opt, dict):
        opt.setdefault("attention_impl", "auto")
        opt.setdefault("attention_bf16", False)
        opt.setdefault("overlap_allgather", False)
        opt.setdefault("prepartition_inputs", True)
    health = c.setdefault("health", {})
    if isinstance(health, dict):
        health.setdefault("on_nan", "warn")
        health.setdefault("rollback_window", 8)
        health.setdefault("max_rollbacks", 3)
        health.setdefault("step_timeout_sec", 0)
    pre = c.setdefault("preemption", {})
    if isinstance(pre, dict):
        pre.setdefault("emergency_checkpoint", True)
        pre.setdefault("budget_safety_factor", 1.5)
        pre.setdefault("budget_margin_sec", 2.0)
    return c


def merge(config: Dict[str, Any], defaults: Dict[str, Any]) -> Dict[str, Any]:
    """Merge cluster-level defaults under the user config (reference:
    task_container_defaults merging in pkg/schemas/expconf/merge logic).
    User values win; dicts merge recursively; lists replace."""
    out = copy.deepcopy(defaults)

    def _merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                _merge(dst[k], v)
            else:
                dst[k] = copy.deepcopy(v)

    _merge(out, config)
    return out


def check(config: Dict[str, Any]) -> Dict[str, Any]:
    """shim + validate + defaults; raises ValueError with all errors."""
    config = shim(config)
    errors = validate(config)
    if errors:
        raise ValueError("invalid experiment config:\n  " + "\n  ".join(errors))
    return apply_defaults(config)
