"""Shared utilities: master API session, storage backends, logging."""

from determined_tpu.common.api import Session  # noqa: F401
