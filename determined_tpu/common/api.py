"""Minimal HTTP client for the master REST API.

Stdlib-only (urllib) analogue of the reference's Session/bindings layer
(harness/determined/common/api/). The API surface it speaks is the ~25
endpoints a trial container actually uses (SURVEY.md Appendix A).
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import random
import socket
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Any, Dict, Optional


def _https_context() -> Optional[ssl.SSLContext]:
    """TLS context for https:// masters. DET_MASTER_CERT_FILE pins the CA
    bundle the server chain must anchor in (reference
    common/api/certs.py); unset = system roots. Self-signed deploy certs
    are their own CA, so hostname checking is off and trust comes from
    the pinned bundle — exactly the reference's cert-pinning posture."""
    cert_file = os.environ.get("DET_MASTER_CERT_FILE", "")
    if cert_file:
        ctx = ssl.create_default_context(cafile=cert_file)
        ctx.check_hostname = False
        return ctx
    return ssl.create_default_context()


def salted_hash(username: str, password: str) -> str:
    """Client-side salted password hash.

    The master stores and compares this opaque string verbatim (reference:
    the CLI sends the already-salted hash, common/api/authentication.py) —
    raw passwords never reach the wire or the DB. Empty password maps to
    empty string (the bootstrap-user posture).
    """
    if not password:
        return ""
    salted = f"determined-tpu${username}${password}".encode()
    return hashlib.sha256(salted).hexdigest()


class APIError(Exception):
    def __init__(self, status: int, body: str, url: str):
        super().__init__(f"HTTP {status} from {url}: {body[:500]}")
        self.status = status
        self.body = body
        self.url = url


class Session:
    """Authenticated master session with retry on transient failures.

    Retry policy (chaos-hardened, see docs/chaos.md):
      - capped exponential backoff with FULL jitter between attempts
        (sleep ~ U(0, min(cap, base * 2**attempt)));
      - a `Retry-After: <seconds>` response header sets the floor for the
        next sleep; 429 is always retried;
      - 502/503/504 are retried for every method (gateway-transient);
        500 and other 5xx are retried only when the request is safe to
        repeat — GETs, and POSTs carrying an idempotency key;
      - POSTs sent with `idempotent=True` get an `X-Idempotency-Key`
        header, generated once per logical request, so the master can
        answer a retry from its replay cache instead of re-applying the
        mutation (a re-sent metric report cannot double-count).
    """

    def __init__(
        self,
        master_url: str,
        token: Optional[str] = None,
        max_retries: int = 8,
        timeout: float = 30.0,
        backoff_base: float = 0.1,
        backoff_cap: float = 5.0,
        headers: Optional[Dict[str, str]] = None,
    ):
        self.master_url = master_url.rstrip("/")
        self.token = token
        self.max_retries = max_retries
        self.timeout = timeout
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        # Extra headers sent with every request — the allocation context
        # installs X-Allocation-Epoch here so every state-mutating call
        # carries the fencing token (docs/cluster-ops.md "Leases, fencing
        # & split-brain").
        self.headers = dict(headers) if headers else {}
        self._ssl_ctx = (
            _https_context() if self.master_url.startswith("https://") else None
        )

    def _backoff(self, attempt: int, retry_after: Optional[float]) -> None:
        delay = random.uniform(
            0.0, min(self.backoff_cap, self.backoff_base * (2.0 ** attempt))
        )
        if retry_after is not None:
            delay = max(delay, retry_after)
        time.sleep(delay)

    @classmethod
    def login(cls, master_url: str, user: str = "determined",
              password: str = "") -> "Session":
        s = cls(master_url)
        resp = s.post("/api/v1/auth/login",
                      body={"username": user,
                            "password": salted_hash(user, password)})
        s.token = resp["token"]
        return s

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        params: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
        idempotent: bool = False,
    ) -> Any:
        url = self.master_url + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None}
            )
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        headers.update(self.headers)
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if idempotent and method not in ("GET", "HEAD"):
            # One key per LOGICAL request: every retry below re-sends the
            # same key, so the master replays rather than re-applies.
            headers["X-Idempotency-Key"] = uuid.uuid4().hex
        safe_to_repeat = method in ("GET", "HEAD") or idempotent
        last_exc: Optional[Exception] = None
        retry_after: Optional[float] = None
        for attempt in range(self.max_retries):
            if attempt:
                self._backoff(attempt - 1, retry_after)
            retry_after = None
            req = urllib.request.Request(url, data=data, headers=headers, method=method)
            try:
                with urllib.request.urlopen(req, timeout=timeout or self.timeout,
                                            context=self._ssl_ctx) as resp:
                    text = resp.read().decode()
                    return json.loads(text) if text else None
            except urllib.error.HTTPError as e:
                body_text = e.read().decode(errors="replace")
                retryable = e.code == 429 or e.code in (502, 503, 504) or (
                    500 <= e.code < 600 and safe_to_repeat
                )
                if retryable and attempt < self.max_retries - 1:
                    ra = e.headers.get("Retry-After") if e.headers else None
                    try:
                        retry_after = float(ra) if ra else None
                    except ValueError:
                        retry_after = None
                    last_exc = e
                else:
                    raise APIError(e.code, body_text, url) from None
            except ssl.SSLCertVerificationError:
                raise  # retrying can't make an untrusted cert trusted
            except (urllib.error.URLError, socket.timeout, ConnectionError,
                    http.client.HTTPException, OSError) as e:
                # http.client.HTTPException covers the mid-RESPONSE failure
                # modes urlopen does NOT wrap in URLError: IncompleteRead /
                # RemoteDisconnected when the peer resets after the status
                # line or partway through the body. Connect-phase errors
                # were always retried; a body cut off mid-read must back
                # off the same way instead of crashing the caller.
                reason = getattr(e, "reason", None)
                if isinstance(reason, ssl.SSLCertVerificationError):
                    raise reason from None
                last_exc = e
        raise ConnectionError(f"master unreachable at {url}: {last_exc}")

    def get(self, path: str, params: Optional[Dict[str, Any]] = None,
            timeout: Optional[float] = None) -> Any:
        return self._request("GET", path, params=params, timeout=timeout)

    def post(self, path: str, body: Optional[Dict[str, Any]] = None,
             params: Optional[Dict[str, Any]] = None,
             idempotent: bool = False) -> Any:
        return self._request("POST", path, body=body, params=params,
                             idempotent=idempotent)

    def patch(self, path: str, body: Optional[Dict[str, Any]] = None) -> Any:
        return self._request("PATCH", path, body=body)

    def delete(self, path: str) -> Any:
        return self._request("DELETE", path)
