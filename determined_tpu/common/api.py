"""Minimal HTTP client for the master REST API.

Stdlib-only (urllib) analogue of the reference's Session/bindings layer
(harness/determined/common/api/). The API surface it speaks is the ~25
endpoints a trial container actually uses (SURVEY.md Appendix A).
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional


def _https_context() -> Optional[ssl.SSLContext]:
    """TLS context for https:// masters. DET_MASTER_CERT_FILE pins the CA
    bundle the server chain must anchor in (reference
    common/api/certs.py); unset = system roots. Self-signed deploy certs
    are their own CA, so hostname checking is off and trust comes from
    the pinned bundle — exactly the reference's cert-pinning posture."""
    cert_file = os.environ.get("DET_MASTER_CERT_FILE", "")
    if cert_file:
        ctx = ssl.create_default_context(cafile=cert_file)
        ctx.check_hostname = False
        return ctx
    return ssl.create_default_context()


def salted_hash(username: str, password: str) -> str:
    """Client-side salted password hash.

    The master stores and compares this opaque string verbatim (reference:
    the CLI sends the already-salted hash, common/api/authentication.py) —
    raw passwords never reach the wire or the DB. Empty password maps to
    empty string (the bootstrap-user posture).
    """
    if not password:
        return ""
    salted = f"determined-tpu${username}${password}".encode()
    return hashlib.sha256(salted).hexdigest()


class APIError(Exception):
    def __init__(self, status: int, body: str, url: str):
        super().__init__(f"HTTP {status} from {url}: {body[:500]}")
        self.status = status
        self.body = body
        self.url = url


class Session:
    """Authenticated master session with retry on transient failures."""

    def __init__(
        self,
        master_url: str,
        token: Optional[str] = None,
        max_retries: int = 5,
        timeout: float = 30.0,
    ):
        self.master_url = master_url.rstrip("/")
        self.token = token
        self.max_retries = max_retries
        self.timeout = timeout
        self._ssl_ctx = (
            _https_context() if self.master_url.startswith("https://") else None
        )

    @classmethod
    def login(cls, master_url: str, user: str = "determined",
              password: str = "") -> "Session":
        s = cls(master_url)
        resp = s.post("/api/v1/auth/login",
                      body={"username": user,
                            "password": salted_hash(user, password)})
        s.token = resp["token"]
        return s

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        params: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> Any:
        url = self.master_url + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None}
            )
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        last_exc: Optional[Exception] = None
        for attempt in range(self.max_retries):
            req = urllib.request.Request(url, data=data, headers=headers, method=method)
            try:
                with urllib.request.urlopen(req, timeout=timeout or self.timeout,
                                            context=self._ssl_ctx) as resp:
                    text = resp.read().decode()
                    return json.loads(text) if text else None
            except urllib.error.HTTPError as e:
                body_text = e.read().decode(errors="replace")
                if e.code in (502, 503, 504) and attempt < self.max_retries - 1:
                    last_exc = e
                else:
                    raise APIError(e.code, body_text, url) from None
            except ssl.SSLCertVerificationError:
                raise  # retrying can't make an untrusted cert trusted
            except (urllib.error.URLError, socket.timeout, ConnectionError, OSError) as e:
                reason = getattr(e, "reason", None)
                if isinstance(reason, ssl.SSLCertVerificationError):
                    raise reason from None
                last_exc = e
            time.sleep(min(2.0 ** attempt * 0.1, 5.0))
        raise ConnectionError(f"master unreachable at {url}: {last_exc}")

    def get(self, path: str, params: Optional[Dict[str, Any]] = None,
            timeout: Optional[float] = None) -> Any:
        return self._request("GET", path, params=params, timeout=timeout)

    def post(self, path: str, body: Optional[Dict[str, Any]] = None,
             params: Optional[Dict[str, Any]] = None) -> Any:
        return self._request("POST", path, body=body, params=params)

    def patch(self, path: str, body: Optional[Dict[str, Any]] = None) -> Any:
        return self._request("PATCH", path, body=body)

    def delete(self, path: str) -> Any:
        return self._request("DELETE", path)
