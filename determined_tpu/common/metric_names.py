"""Single source of truth for every exported metric and span name.

The master (C++), agent (C++), serving replicas (Python) and the harness
all publish observability data; this registry is what keeps them from
drifting apart on the same gauge (docs/observability.md). `make lint`
runs determined_tpu/analysis/metric_lint.py, which checks BOTH directions:

  - every `det_*` metric name and every span name emitted anywhere in the
    scanned sources must be registered here, and
  - every registered name must still be emitted somewhere (a stale
    registry row is drift too).

Naming rules (enforced by the lint):
  - metric names: snake_case, `det_` prefix; counters end `_total`;
    time/size-bearing names carry a unit suffix (`_seconds`, `_ms`,
    `_us`, `_bytes`, `_lines`);
  - span names: lowercase dot-separated segments
    (`component.phase[.subphase]`).
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

# name -> (prometheus type, help)
MASTER_METRICS: Dict[str, Tuple[str, str]] = {
    "det_agents_alive": ("gauge", "Agents with a live heartbeat"),
    "det_slots_total": ("gauge", "Slots on alive agents"),
    "det_slots_free": ("gauge", "Enabled, unallocated slots on alive agents"),
    "det_slots_allocated": ("gauge", "Slots bound to an allocation"),
    "det_slots_draining": ("gauge", "Slots on DRAINING agents"),
    "det_scheduler_queue_depth": ("gauge", "Allocations waiting for resources"),
    "det_scheduler_queue_wait_seconds": (
        "histogram", "Submit-to-placement wait per allocation"),
    "det_allocations": ("gauge", "Allocations by state"),
    "det_experiments": ("gauge", "Experiments by state"),
    "det_preemptions_total": ("counter", "Allocation preemptions issued"),
    "det_resizes_total": ("counter", "Elastic allocation-size transitions"),
    "det_trial_requeues_total": (
        "counter", "Trial container restarts re-queued by the master"),
    "det_idempotency_replays_total": (
        "counter", "POSTs answered from the idempotency replay cache"),
    "det_stream_backlog_events": (
        "gauge", "Entity-change events buffered for /api/v1/stream"),
    "det_trial_spans_ingested_total": (
        "counter", "Trace spans accepted by POST /trials/{id}/spans"),
    "det_compile_jobs": (
        "gauge", "Compile-farm AOT jobs by state (docs/compile-farm.md)"),
    "det_compile_artifact_uploads_total": (
        "counter", "Compile-artifact batches stored by POST /compile_cache"),
    "det_compile_artifact_fetches_total": (
        "counter", "Compile-artifact fetches served by GET /compile_cache"),
    "det_compile_links_total": (
        "counter", "Fingerprint-verified executable shares between "
                   "signatures"),
    "det_deployment_replicas": (
        "gauge", "Serving-deployment replicas by state "
                 "(ready/starting/draining; docs/serving.md)"),
    "det_deployment_target_replicas": (
        "gauge", "Replica count the deployment controller is steering to"),
    "det_deployment_scale_events_total": (
        "counter", "Autoscaler/manual deployment scale decisions by "
                   "direction"),
    "det_serve_router_retries_total": (
        "counter", "Requests retried onto another replica after a "
                   "connection refusal"),
    "det_serve_router_ejections_total": (
        "counter", "Replica circuit-breaker ejections by the serve router"),
    "det_serve_request_seconds": (
        "histogram", "End-to-end serving request latency per deployment, "
        "merged from fresh replica heartbeats (docs/serving.md 'Request "
        "latency & SLOs')"),
    "det_request_spans_ingested_total": (
        "counter", "Serving request spans accepted by "
        "POST /allocations/{id}/request_spans"),
    "det_serve_slo_breaches_total": (
        "counter", "Routed generations whose wall time exceeded the "
        "deployment's serving.slo_ms"),
    "det_serve_cold_starts_total": (
        "counter", "Scale-from-zero demand wakes: the router bumped a "
        "deployment's target 0 -> 1 and held the request "
        "(docs/serving.md 'Scale to zero')"),
    "det_deployment_swaps_total": (
        "counter", "Completed rolling weight swaps: every serving "
        "replica reached the updated model version "
        "(docs/serving.md 'Model lifecycle')"),
    "det_model_versions_registered_total": (
        "counter", "Model versions registered (API registration + "
        "registry: auto-promotion on experiment completion)"),
    "det_serve_canary_requests_total": (
        "counter", "Routed generations by version group "
        "(canary/stable) per deployment while a canary split is active"),
    "det_provisioner_demand_slots": (
        "gauge", "Composed provisioner demand by pool and source "
        "(pending/elastic/serving/compile; docs/cluster-ops.md "
        "'Capacity loop')"),
    "det_provisioner_nodes": (
        "gauge", "Provisioner-managed cloud nodes by pool and state "
        "(CREATING/READY/DELETING)"),
    "det_provisioner_create_failures_total": (
        "counter", "Cloud node-create failures (each arms the per-pool "
        "exponential backoff)"),
    "det_api_requests_total": ("counter", "API requests by status code"),
    "det_api_request_seconds": (
        "histogram", "API request latency by route family"),
    "det_fenced_writes_total": (
        "counter", "State-mutating API calls rejected with 409 because the "
        "caller's X-Allocation-Epoch was superseded, by route "
        "(docs/cluster-ops.md 'Leases, fencing & split-brain'). Nonzero "
        "without a partition event means a zombie writer survived "
        "reassignment"),
    "det_lease_expirations_total": (
        "counter", "Agent ownership leases that lapsed without a heartbeat "
        "renewal; the agent is expected to have self-fenced its tasks"),
    "det_master_db_tx_total": (
        "counter", "Explicit DB transactions opened (BEGIN IMMEDIATE). The "
        "group-commit bench gates on the COUNTED ratio of this with "
        "batching on vs off (docs/cluster-ops.md 'Overload, quotas & "
        "fair use')"),
    "det_master_write_queue_depth": (
        "gauge", "Writes parked in the group-commit queue awaiting the "
        "next flush; at queue_cap new writes get 429 + Retry-After"),
    "det_master_write_batch_events": (
        "histogram", "Writes coalesced per group-commit flush (batch "
        "size distribution; 1 everywhere means batching is buying "
        "nothing)"),
    "det_master_write_flush_seconds": (
        "histogram", "Group-commit flush transaction latency — the "
        "brownout controller's 'DB write latency' signal"),
    "det_master_shed_total": (
        "counter", "Interactive requests shed with the brownout 503 by "
        "route family; trial-critical families NEVER appear here"),
    "det_rate_limited_total": (
        "counter", "Requests refused with 429 by the per-tenant token "
        "bucket, labeled by the charged principal"),
}

AGENT_METRICS: Dict[str, Tuple[str, str]] = {
    "det_agent_slots": ("gauge", "Slots this agent registered"),
    "det_agent_tasks": ("gauge", "Supervised tasks by state"),
    "det_agent_log_backlog_lines": (
        "gauge", "Task-log lines queued or in flight to the master"),
    "det_agent_draining": (
        "gauge", "1 after a termination notice was posted, else 0"),
    "det_agent_lease_remaining_seconds": (
        "gauge", "Seconds until this agent's ownership lease lapses and it "
        "self-fences its tasks (renewed by every heartbeat ack; "
        "docs/cluster-ops.md 'Leases, fencing & split-brain')"),
    "det_agent_uptime_seconds": ("gauge", "Seconds since the agent started"),
}

SERVE_METRICS: Dict[str, Tuple[str, str]] = {
    "det_serve_queue_depth": ("gauge", "Admission-queue depth"),
    "det_serve_active_requests": ("gauge", "Requests joined into the batch"),
    "det_serve_kv_blocks_free": ("gauge", "Free KV cache blocks"),
    "det_serve_kv_blocks_used": ("gauge", "KV cache blocks held by "
                                 "admitted sequences (paged layout)"),
    "det_serve_kv_blocks_total": ("gauge", "Total KV cache blocks"),
    "det_serve_prefix_cache_hit_rate": (
        "gauge", "Prompt tokens served from cached prefix blocks / prompt "
        "tokens seen (docs/serving.md 'Paged KV & prefix caching')"),
    "det_serve_requests_total": ("counter", "Requests completed"),
    "det_serve_tokens_total": ("counter", "Tokens generated"),
    "det_serve_draining": ("gauge", "1 while draining, else 0"),
    # Token-latency SLO histograms (docs/serving.md "Request latency &
    # SLOs") — also on the replica heartbeat, aggregated per deployment.
    "det_serve_ttft_seconds": (
        "histogram", "Submit to first generated token, per request"),
    "det_serve_tpot_seconds": (
        "histogram", "Mean inter-token interval per request "
        "(time-per-output-token)"),
    "det_serve_e2e_seconds": (
        "histogram", "Submit to final token, per request"),
    "det_serve_queue_wait_seconds": (
        "histogram", "Submit to batch admission, per request"),
}

# span name -> (emitting component, help)
SPAN_NAMES: Dict[str, Tuple[str, str]] = {
    "trial.lifecycle": (
        "master", "Root span: trial submit to terminal state"),
    "trial.queue_wait": (
        "master", "Allocation submit to placement (per container run)"),
    "agent.image_setup": (
        "agent", "Workdir + log-file preparation before fork"),
    "agent.container_start": (
        "agent", "Fork to the RUNNING report"),
    "agent.log_drain": (
        "agent", "Final log drain before the exit report"),
    "agent.cache_warm": (
        "agent", "Compile-farm artifact prefetch, overlapped with image "
                 "setup"),
    "agent.lease": (
        "agent", "Ownership-lease lapse to self-fence kill on a partitioned "
        "agent; lease_ttl_s and container_id in attrs (best-effort: lost "
        "when the partition is real, delivered in chaos runs)"),
    "harness.compile": (
        "harness", "First executable acquisition (AOT load or "
                   "trace+compile); cache_hit/signature/attention_impl "
                   "in attrs"),
    "harness.restore": (
        "harness", "Checkpoint restore (lineage walk included)"),
    "harness.reshard": (
        "harness", "Elastic in-process re-mesh: rebuild + resharding restore"),
    "harness.validate": (
        "harness", "One validation pass"),
    "harness.checkpoint.save": (
        "harness", "Checkpoint phase 1: synchronous orbax save portion"),
    "harness.checkpoint.commit": (
        "harness", "Checkpoint phase 2: manifest + COMMIT + COMPLETED report"),
    "harness.checkpoint.emergency": (
        "harness", "Deadline-budgeted emergency checkpoint on preemption"),
    "harness.resize.downtime": (
        "harness", "Resize signal to first post-resize readiness"),
    # Serving request-path spans (docs/observability.md "Request spans"):
    # one trace per served request, trace id == X-Request-Id.
    "serve.request": (
        "serve", "Root span: request submit to finish on the replica "
        "(span_id == request id)"),
    "serve.queue_wait": (
        "serve", "Admission-queue wait: submit to batch join"),
    "serve.prefill": (
        "serve", "Prompt prefill; bucket/suffix_len/prefix_cache_hit/"
        "blocks in attrs"),
    "serve.decode": (
        "serve", "Token generation: first token to finish; tokens/steps/"
        "occupancy_at_admit in attrs"),
    "serve.router.dispatch": (
        "master", "One router forward attempt: replica chosen, retries, "
        "breaker state in attrs (a retried request shows two)"),
    "serve.cold_start": (
        "master", "Scale-from-zero hold: how long the router parked the "
        "waking request and whether the replica's engine deserialized "
        "(warm AOT) or traced — wait_ms/budget_s/replica/engine_source "
        "in attrs"),
    "serve.swap": (
        "master", "One rolling weight swap, update to last stale "
        "replica drained — from/to versions and replicas_swapped in "
        "attrs (docs/serving.md 'Model lifecycle')"),
}

_METRIC_RE = re.compile(r"^det(_[a-z0-9]+)+$")
_SPAN_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
_UNIT_SUFFIXES = ("_total", "_seconds", "_ms", "_us", "_bytes", "_lines",
                  "_events", "_depth", "_requests")
# Words that imply a measured quantity and therefore REQUIRE a unit suffix.
_UNIT_WORDS = ("seconds", "latency", "duration", "wait", "size", "backlog",
               "uptime")


def all_metrics() -> Dict[str, Tuple[str, str]]:
    out: Dict[str, Tuple[str, str]] = {}
    out.update(MASTER_METRICS)
    out.update(AGENT_METRICS)
    out.update(SERVE_METRICS)
    return out


def check_registry() -> list:
    """Self-consistency: names conform to the naming rules. Returns a list
    of violation strings (empty = clean)."""
    problems = []
    for name, (mtype, _) in all_metrics().items():
        if not _METRIC_RE.match(name):
            problems.append(f"metric {name!r}: not snake_case det_*")
        if mtype == "counter" and not name.endswith("_total"):
            problems.append(f"counter {name!r}: must end in _total")
        if any(w in name for w in _UNIT_WORDS) and not name.endswith(
                _UNIT_SUFFIXES):
            problems.append(
                f"metric {name!r}: measured quantity without a unit suffix "
                f"({'/'.join(_UNIT_SUFFIXES)})")
    for name in SPAN_NAMES:
        if not _SPAN_RE.match(name):
            problems.append(
                f"span {name!r}: must be lowercase dot-separated segments")
    return problems
