"""Streaming updates client (reference
harness/determined/common/streams/_client.py over the master's websocket
publisher; here a long-poll generator over GET /api/v1/stream)."""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

from determined_tpu.common.api import Session


class StreamClient:
    """Iterate entity-change events from the master.

        for event in StreamClient(session).subscribe(["experiments"]):
            ...  # {"seq": N, "entity": "experiments", "payload": {...}}

    `dropped=True` responses mean the server's ring overflowed past our
    cursor — the caller should re-list the entities it mirrors, then keep
    streaming (reference subscribers resync from the DB on overflow).
    """

    def __init__(self, session: Session, since: int = 0):
        self._session = session
        self.since = since
        self.dropped = False

    def poll(self, entities: Optional[Sequence[str]] = None,
             timeout_seconds: float = 30.0) -> list:
        params = {
            "since": str(self.since),
            "timeout_seconds": str(timeout_seconds),
        }
        if entities:
            params["entities"] = ",".join(entities)
        out = self._session.get("/api/v1/stream", params=params)
        events = out.get("events", [])
        # Overflow surfaces twice: the response-level `dropped` flag and a
        # synthetic `resync` event at the head of the batch — a consumer
        # that only walks events still learns it must re-list.
        self.dropped = (self.dropped or bool(out.get("dropped"))
                        or any(e.get("entity") == "resync" for e in events))
        if events:
            self.since = events[-1]["seq"]
        return events

    def subscribe(self, entities: Optional[Sequence[str]] = None,
                  timeout_seconds: float = 30.0) -> Iterator[dict]:
        """Infinite generator; blocks in long-polls between event batches."""
        while True:
            for event in self.poll(entities, timeout_seconds):
                yield event
