"""Trial-lifecycle tracing: spans from submit to step (docs/observability.md).

A span is `{trace_id, span_id, parent, name, start_us, end_us, attrs}` with
wall-clock epoch microseconds, so master/agent/harness spans from different
hosts land on one timeline. The master opens the root span (span_id ==
trace_id) at trial submit and propagates the trace id to every container as
`DET_TRACE_ID`; everything the harness emits parents to that root unless
nested under an enclosing `span()` context.

Always-on cheap: `span()`/`emit()` append to an in-memory buffer — no I/O,
no locks on the step critical path (span emission happens at phase
boundaries, never per step). The buffer is flushed alongside the metrics
flush via `flush()`, POSTing one idempotency-keyed batch to
`POST /api/v1/trials/{id}/spans`. A lost span sink must never hurt the
trial: flush failures log and drop (the `trace.span.drop` fault point
proves that path deterministically, docs/chaos.md).

Span names are registered in common/metric_names.py (SPAN_NAMES); the
metric/span lint keeps emitters and registry in sync.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from determined_tpu.common import faultpoint

logger = logging.getLogger("determined_tpu.common")

FAULT_SPAN_DROP = "trace.span.drop"


def now_us() -> int:
    """Wall-clock epoch microseconds (all components share this domain)."""
    return int(time.time() * 1e6)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    __slots__ = ("trace_id", "span_id", "parent", "name", "start_us",
                 "end_us", "attrs")

    def __init__(self, trace_id: str, name: str, parent: str = "",
                 start_us: Optional[int] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent = parent
        self.name = name
        self.start_us = start_us if start_us is not None else now_us()
        self.end_us = 0
        self.attrs = dict(attrs or {})

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent": self.parent,
            "name": self.name,
            "start_us": self.start_us,
            "end_us": self.end_us,
            "attrs": self.attrs,
        }


class Tracer:
    """Buffered span emitter for one trial process.

    Chief-only on multi-host trials (non-chief construction yields a
    disabled tracer); local/masterless mode buffers into `local_spans` so
    the same instrumentation is inspectable without a cluster.
    `DET_TRACE_OFF=1` disables emission entirely (the bench A/B switch).
    """

    def __init__(
        self,
        session=None,
        trial_id: int = 0,
        trace_id: Optional[str] = None,
        enabled: Optional[bool] = None,
    ):
        self._session = session
        self._trial_id = trial_id
        self.trace_id = trace_id or os.environ.get("DET_TRACE_ID") or \
            uuid.uuid4().hex[:16]
        if enabled is None:
            enabled = os.environ.get("DET_TRACE_OFF", "") not in ("1", "true")
        self.enabled = enabled
        # The root span lives master-side with span_id == trace_id; local
        # mode has no master, so parentage still resolves to the trace id.
        self.root_span_id = self.trace_id
        self._buf: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._tls = threading.local()  # per-thread current-parent stack
        # Local mode keeps every span ever emitted (tests, `bench.py`).
        self.local_spans: List[Dict[str, Any]] = []
        self.dropped = 0  # batches lost to sink failure (observability only)

    # -- emission ------------------------------------------------------

    def _parent(self) -> str:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else self.root_span_id

    def emit(self, name: str, start_us: int, end_us: int,
             attrs: Optional[Dict[str, Any]] = None,
             parent: Optional[str] = None) -> Optional[Span]:
        """Record a completed span (buffer append only; no I/O)."""
        if not self.enabled:
            return None
        sp = Span(self.trace_id, name,
                  parent=parent if parent is not None else self._parent(),
                  start_us=start_us, attrs=attrs)
        sp.end_us = end_us
        rec = sp.to_dict()
        with self._lock:
            self._buf.append(rec)
        return sp

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context manager: times the block, nests children under it.

        Yields the Span (attrs may be amended inside the block); exceptions
        propagate after the span is recorded with `error` set.
        """
        if not self.enabled:
            yield None
            return
        sp = Span(self.trace_id, name, parent=self._parent(), attrs=attrs)
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(sp.span_id)
        try:
            yield sp
        except BaseException as e:
            sp.attrs["error"] = type(e).__name__
            raise
        finally:
            stack.pop()
            sp.end_us = now_us()
            with self._lock:
                self._buf.append(sp.to_dict())

    # -- flushing ------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    def flush(self) -> int:
        """Ship the buffered batch. Off the step critical path — called at
        metric-flush boundaries and close(). Never raises: a dead span sink
        must not take the trial with it. Returns spans shipped (or locally
        recorded)."""
        with self._lock:
            if not self._buf:
                return 0
            batch, self._buf = self._buf, []
        if faultpoint.fire(FAULT_SPAN_DROP) is not faultpoint.Action.NONE:
            logger.warning("faultpoint dropped %d span(s)", len(batch))
            self.dropped += 1
            return 0
        if self._session is None:
            self.local_spans.extend(batch)
            return len(batch)
        try:
            # idempotent: a retry after a lost response must not
            # double-insert the batch (master dedupes by span_id anyway —
            # the header saves it the writes).
            self._session.post(
                f"/api/v1/trials/{self._trial_id}/spans",
                body={"spans": batch},
                idempotent=True,
            )
            return len(batch)
        except Exception:
            # Tracing is best-effort by contract: drop the batch, keep
            # training (docs/chaos.md `trace.span.drop`).
            self.dropped += 1
            logger.warning("span flush failed; dropped %d span(s)",
                           len(batch), exc_info=True)
            return 0

    def close(self) -> None:
        self.flush()


def render_waterfall(spans: List[Dict[str, Any]], width: int = 48) -> str:
    """Text waterfall for `det trial trace` — one line per span, indented
    by parentage, with an offset-scaled duration bar."""
    if not spans:
        return "(no spans)"
    spans = sorted(spans, key=lambda s: (int(s.get("start_us", 0) or 0)))
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}

    def depth(s, limit=16):
        d, cur = 0, s
        while d < limit:
            p = cur.get("parent") or ""
            if not p or p not in by_id or p == cur.get("span_id"):
                break
            cur = by_id[p]
            d += 1
        return d

    t0 = min(int(s.get("start_us", 0) or 0) for s in spans)
    ends = [int(s.get("end_us", 0) or 0) for s in spans]
    t1 = max([e for e in ends if e] + [t0 + 1])
    scale = max(t1 - t0, 1)
    name_w = max(len("  " * depth(s) + s.get("name", "?")) for s in spans)
    lines = [f"{'span':<{name_w}}  {'start_ms':>9} {'dur_ms':>9}  timeline"]
    for s in spans:
        start = int(s.get("start_us", 0) or 0)
        end = int(s.get("end_us", 0) or 0)
        off_ms = (start - t0) / 1000.0
        dur_ms = (end - start) / 1000.0 if end else float("nan")
        lo = int((start - t0) / scale * width)
        hi = int(((end if end else t1) - t0) / scale * width)
        bar = " " * lo + ("#" * max(hi - lo, 1) if end else "~" * max(width - lo, 1))
        label = "  " * depth(s) + s.get("name", "?")
        dur = f"{dur_ms:9.1f}" if end else "  running"
        lines.append(f"{label:<{name_w}}  {off_ms:9.1f} {dur}  |{bar}|")
    return "\n".join(lines)
