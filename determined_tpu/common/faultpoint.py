"""Python-side fault points — the harness mirror of native/common/faultpoint.

The native master/agent compile in named fault points armed via
``DET_FAULTS`` (docs/chaos.md). Training-side Python subsystems (the async
input pipeline, checkpointing) need the same lever so chaos runs can
exercise *harness* recovery paths — an iterator dying mid-epoch, a stalled
H2D queue — with the exact same grammar and determinism guarantees:

    DET_FAULTS=point:mode[:param][,point:mode[:param]...]

Modes: ``error`` (raise FaultInjected at the call site), ``drop`` (swallow
the operation — e.g. skip queuing a batch), ``delay-<ms>`` (sleep, then
proceed), ``crash`` (``os._exit(137)``). The optional param is an integer
count (fire N times then auto-disarm) or a probability (``0.3`` / ``30%``)
drawn from a PRNG seeded by ``DET_FAULTS_SEED`` so runs are reproducible.

Unarmed points cost one module-global check. Call sites use::

    action = faultpoint.fire("data.prefetch.queue")
    if action is Action.ERROR:
        raise FaultInjected("data.prefetch.queue")
    if action is Action.DROP:
        continue
"""

from __future__ import annotations

import enum
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("determined_tpu.common")


class Action(enum.Enum):
    NONE = "none"    # not armed / did not fire — proceed normally
    ERROR = "error"  # the call site must fail the operation
    DROP = "drop"    # the call site must swallow the operation


class FaultInjected(RuntimeError):
    """Raised by call sites honoring an `error`-mode fault point."""

    def __init__(self, point: str):
        super().__init__(f"fault injected at {point!r} (DET_FAULTS)")
        self.point = point


class _Arm:
    def __init__(self, mode: str, count: int, probability: float):
        self.mode = mode            # error | drop | crash | delay-<ms>
        self.count = count          # >0: fire N times then disarm; else ∞
        self.probability = probability  # (0,1] gates each hit; 0 = always
        self.fired = 0


_lock = threading.Lock()
_arms: Dict[str, _Arm] = {}
_n_armed = 0  # fast-path check without the lock
_rng: Optional[random.Random] = None
_env_loaded = False


def _get_rng() -> random.Random:
    global _rng
    if _rng is None:
        _rng = random.Random(int(os.environ.get("DET_FAULTS_SEED", "1337")))
    return _rng


def arm(point: str, mode: str, count: int = 0,
        probability: float = 0.0) -> None:
    """Arm `point`. See module docstring for mode/param semantics."""
    global _n_armed
    if mode not in ("error", "drop", "crash") and \
            not mode.startswith("delay-"):
        raise ValueError(f"faultpoint: unknown mode {mode!r}")
    if mode.startswith("delay-"):
        int(mode[len("delay-"):])  # validate now, not at fire time
    with _lock:
        _arms[point] = _Arm(mode, count, probability)
        _n_armed = len(_arms)


def disarm(point: str) -> None:
    global _n_armed
    with _lock:
        _arms.pop(point, None)
        _n_armed = len(_arms)


def disarm_all() -> None:
    global _n_armed, _env_loaded
    with _lock:
        _arms.clear()
        _n_armed = 0
        _env_loaded = True  # explicit reset wins over the env spec


def armed() -> List[str]:
    with _lock:
        return sorted(_arms)


def arm_from_spec(spec: str) -> None:
    """DET_FAULTS grammar: point:mode[:param][,point:mode[:param]...]."""
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(f"faultpoint: bad entry {entry!r}")
        point, mode = parts[0], parts[1]
        count, probability = 0, 0.0
        if len(parts) >= 3 and parts[2]:
            param = parts[2]
            if param.endswith("%"):
                probability = float(param[:-1]) / 100.0
            elif "." in param:
                probability = float(param)
            else:
                count = int(param)
        arm(point, mode, count=count, probability=probability)


def reload_env() -> None:
    """Drop all arms and re-read DET_FAULTS (test hook; the native services
    only read the env at process start)."""
    global _env_loaded
    disarm_all()
    _env_loaded = False
    _load_env_once()


def _load_env_once() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get("DET_FAULTS", "")
    if not spec:
        return
    try:
        arm_from_spec(spec)
        logger.warning("faultpoint: armed from DET_FAULTS=%s", spec)
    except (ValueError, TypeError) as e:
        logger.error("faultpoint: DET_FAULTS rejected: %s", e)


def fire(point: str) -> Action:
    """Hot-path hook: applies delay/crash internally, returns the action
    the call site must honor. Decrements counted arms."""
    global _n_armed
    _load_env_once()
    if not _n_armed:
        return Action.NONE
    with _lock:
        a = _arms.get(point)
        if a is None:
            return Action.NONE
        if a.probability and _get_rng().random() >= a.probability:
            return Action.NONE
        a.fired += 1
        if a.count > 0 and a.fired >= a.count:
            del _arms[point]
            _n_armed = len(_arms)
        mode = a.mode
    if mode == "crash":
        logger.error("faultpoint: %s crash — _exit(137)", point)
        os._exit(137)
    if mode.startswith("delay-"):
        time.sleep(int(mode[len("delay-"):]) / 1000.0)
        return Action.NONE
    return Action.ERROR if mode == "error" else Action.DROP
