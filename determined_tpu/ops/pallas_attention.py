"""Compatibility shim — the Pallas flash-attention training kernel moved
to `ops/flash_attention.py` (PR 18), which unifies the kernel, the jnp
reference path, and the `optimizations.attention_impl` dispatcher in one
module and shares its grid/scratch plumbing with the decode kernel via
`ops/_pallas_common.py`. Import from `determined_tpu.ops.flash_attention`
in new code."""

from determined_tpu.ops.flash_attention import (  # noqa: F401
    _flash,
    _flash_bwd,
    _flash_fwd,
    pallas_flash_attention,
)
