"""Pallas TPU flash attention (fwd + custom-vjp bwd).

The MFU-critical kernel for the GPT-2 north star (BASELINE.md; SURVEY.md §7
"Hard parts" (f): ≥40% MFU demands fused attention). Tiled causal attention
with online softmax: the S×S logits matrix never round-trips through HBM —
each [block_q, block_k] tile lives in VMEM, is accumulated in fp32, and only
the [S, D] output (plus per-row logsumexp stats for the backward) is written
back.

Layout: kernels operate on [BH, S, D] (batch×heads flattened); the public
wrapper accepts the model's [B, S, H, D] and transposes. Block sizes default
to MXU/VMEM-friendly 256/512 tiles; the grid walks (bh, q-block) with the
K/V buffers for a given bh held in VMEM across its q blocks (pallas skips
the re-fetch when a block index repeats between consecutive programs).

Backward is the standard two-kernel flash split:
  - dq kernel: grid over q blocks, inner loop over visible k blocks;
  - dk/dv kernel: grid over k blocks, inner loop over visible q blocks;
with p = exp(s - L) recomputed from the saved logsumexp L (no max pass
needed) and delta = rowsum(dO ∘ O) precomputed in XLA.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _pick_blocks(s: int) -> Tuple[int, int]:
    """(block_q, block_k) tuned for v5e VMEM; both divide s (s % 128 == 0)."""
    block_q = min(512, s)
    block_k = min(512, s)
    while s % block_q:
        block_q //= 2
    while s % block_k:
        block_k //= 2
    return block_q, block_k


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                causal):
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    s = k_ref.shape[1]
    num_k = s // block_k

    q = q_ref[0]  # [block_q, d]

    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    if causal:
        # Only k blocks that intersect the visible triangle.
        upper = jax.lax.div(qi * block_q + block_q - 1, block_k) + 1
    else:
        upper = num_k

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        st = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            st = jnp.where(rows >= cols, st, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(st, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(st - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha + pv
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc, m, l))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)  # [block_q, 1]


def _flash_fwd(q, k, v, causal: bool):
    """q,k,v: [BH, S, D] → (o [BH,S,D], lse [BH,S] fp32)."""
    bh, s, d = q.shape
    block_q, block_k = _pick_blocks(s)
    scale = 1.0 / math.sqrt(d)
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_k=block_k, causal=causal)
    flops_per_bh = 4 * s * s * d * (0.5 if causal else 1.0)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            # trailing unit dim: TPU block tiling needs the last dim to match
            # the array (per-row stats can't be a bare [bh, s] block)
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(flops_per_bh * bh),
            bytes_accessed=int(3 * bh * s * d * q.dtype.itemsize),
            transcendentals=int(bh * s * s * (0.5 if causal else 1.0)),
        ),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, block_k, causal):
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    s = k_ref.shape[1]
    num_k = s // block_k

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]     # [block_q, 1]
    delta = delta_ref[0]

    if causal:
        upper = jax.lax.div(qi * block_q + block_q - 1, block_k) + 1
    else:
        upper = num_k

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        st = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(st - lse)  # ≤ 1; lse is the exact logsumexp
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        dq = dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dq

    dq = jax.lax.fori_loop(0, upper, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, *, scale, block_q, causal):
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    s = q_ref.shape[1]
    num_q = s // block_q

    k = k_ref[0]  # [block_k, d]
    v = v_ref[0]

    lower = jax.lax.div(ki * block_k, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]    # [block_q, 1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        st = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        p = jnp.exp(st - lse)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        pt = p.astype(do_blk.dtype)
        dv = dv + jax.lax.dot_general(
            pt, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, d]
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = (p * (dp - delta) * scale).astype(q_blk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, d]
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        lower, num_q, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal: bool):
    bh, s, d = q.shape
    block_q, block_k = _pick_blocks(s)
    scale = 1.0 / math.sqrt(d)
    # delta_i = sum_d dO_id * O_id — cheap elementwise reduce; let XLA fuse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [bh, s, 1]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_k=block_k,
                          causal=causal),
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                          causal=causal),
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, s, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public op with custom vjp
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, causal):
    o, _ = _flash_fwd(q, k, v, causal)
    return o


def _flash_vjp_fwd(q, k, v, causal):
    o, lse = _flash_fwd(q, k, v, causal)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, causal)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def pallas_flash_attention(q, k, v, causal: bool = True) -> jax.Array:
    """q,k,v: [B, S, H, D] → [B, S, H, D]. Causal fused attention."""
    b, s, h, d = q.shape
    to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    o = _flash(to3(q), to3(k), to3(v), causal)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
