"""Pallas TPU paged decode-attention (vLLM-style block-table gather).

The serving decode hot path (ROADMAP item 2; docs/serving.md "Paged KV &
prefix caching"): each decode step, every active slot attends its single
query token over K/V that live in a **block pool** — `[num_blocks,
block_size, H, Dh]` per layer — addressed through a per-slot **block
table** (`[slots, max_blocks]` int32, logical block i of the sequence →
pool block `table[s, i]`). The dense layout's `slots × max_seq` lane
reservation disappears: HBM holds exactly the blocks sequences actually
own, and admission can pack many more sequences into the same budget.

Two interchangeable implementations (selected by
`serving.attention_impl`, asserted token-identical by tests/test_serving):

  - `paged_attention_reference` — pure-jnp gather (`pool[table]`) +
    the exact masked-softmax arithmetic of the dense decode step. With
    `block_size` dividing `max_seq` the gathered lane has the same
    shape and element order as the dense lane, so greedy decode is
    bit-identical to the dense path. Fast on CPU; the fallback anywhere
    Pallas is unavailable.

  - `paged_attention_pallas` — the TPU kernel. Grid `(slots,
    max_blocks)`; the block table and positions ride
    `PrefetchScalarGridSpec` scalar prefetch so each program's K/V
    BlockSpec `index_map` dereferences `table[s, b]` — the gather IS the
    pipeline's block fetch, no materialized `[slots, max_seq]` lane ever
    exists. The inner loop is an online softmax: fp32 running max `m`,
    normalizer `l`, and accumulator `acc` live in VMEM scratch across
    the `b` iterations of one slot; the output block is written at the
    final block index. Tier-1 runs it on CPU through pallas interpret
    mode (`_jax_compat`); on TPU the same kernel compiles natively.

Inactive slots point every table entry at a reserved trash block and sit
at position 0 — they compute garbage the batcher discards, exactly like
the dense path's stale lanes, so the executable never depends on which
slots are live.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from determined_tpu.ops._pallas_common import (
    HAVE_PALLAS,
    NEG_INF,
    finish_softmax_scratch,
    init_softmax_scratch,
    interpret_default as _interpret_default,
    online_softmax_update,
    softmax_scratch,
)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


# ---------------------------------------------------------------------------
# Reference implementation: gather + dense masked softmax.
# ---------------------------------------------------------------------------


def paged_attention_reference(
    q: jax.Array,             # [slots, H, Dh]
    k_pool: jax.Array,        # [num_pool_blocks, block_size, H, Dh]
    v_pool: jax.Array,        # [num_pool_blocks, block_size, H, Dh]
    block_tables: jax.Array,  # [slots, max_blocks] int32 pool indices
    positions: jax.Array,     # [slots] int32: index written this step
) -> jax.Array:
    """Pure-jnp paged decode attention → [slots, H, Dh] in q.dtype.

    Gathers each slot's lane (`pool[table]` → `[max_blocks × block_size,
    H, Dh]`) and then runs the *identical* arithmetic of the dense decode
    step (serve/model.decode_step): fp32 logits, `index <= position`
    mask, fp32 softmax, probs cast back to the compute dtype. Identical
    shapes + identical op order ⇒ bit-identical greedy decode vs dense.
    """
    slots, mb = block_tables.shape
    bs = k_pool.shape[1]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    k_lane = k_pool[block_tables].reshape(slots, mb * bs, *k_pool.shape[2:])
    v_lane = v_pool[block_tables].reshape(slots, mb * bs, *v_pool.shape[2:])
    mask = jnp.arange(mb * bs)[None] <= positions[:, None]  # [slots, S]
    logits = jnp.einsum("bhd,bmhd->bhm", q, k_lane).astype(jnp.float32)
    logits = jnp.where(mask[:, None], logits * scale,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhm,bmhd->bhd", probs, v_lane)


# ---------------------------------------------------------------------------
# Pallas kernel: scalar-prefetched block-table gather + online softmax.
# ---------------------------------------------------------------------------


def _paged_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, block_size, scale):
    s, b = pl.program_id(0), pl.program_id(1)
    mb = pl.num_programs(1)

    @pl.when(b == 0)
    def _init():
        init_softmax_scratch(acc_ref, m_ref, l_ref)

    pos = pos_ref[s]

    # Blocks past the slot's write position hold nothing visible; their
    # programs still run (the TPU grid is static) but touch no state.
    @pl.when(b * block_size <= pos)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)                       # [H, Dh]
        k = jnp.swapaxes(k_ref[0], 0, 1).astype(jnp.float32)   # [H, bs, Dh]
        v = jnp.swapaxes(v_ref[0], 0, 1).astype(jnp.float32)
        st = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale        # [H, bs]
        idx = b * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        st = jnp.where(idx <= pos, st, NEG_INF)
        online_softmax_update(st, v, acc_ref, m_ref, l_ref,
                              (((1,), (1,)), ((0,), (0,))))    # [H, Dh]

    @pl.when(b == mb - 1)
    def _finish():
        finish_softmax_scratch(o_ref, acc_ref, l_ref, idx=0)


def paged_attention_pallas(
    q: jax.Array,             # [slots, H, Dh]
    k_pool: jax.Array,        # [num_pool_blocks, block_size, H, Dh]
    v_pool: jax.Array,        # [num_pool_blocks, block_size, H, Dh]
    block_tables: jax.Array,  # [slots, max_blocks] int32
    positions: jax.Array,     # [slots] int32
    interpret=None,
) -> jax.Array:
    """Pallas paged decode attention → [slots, H, Dh] in q.dtype."""
    if not HAVE_PALLAS:
        raise RuntimeError(
            "pallas unavailable in this jax build; use "
            "serving.attention_impl: reference")
    slots, nh, dh = q.shape
    bs = k_pool.shape[1]
    mb = block_tables.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # block_tables, positions
        grid=(slots, mb),
        in_specs=[
            pl.BlockSpec((1, nh, dh), lambda s, b, tbl, pos: (s, 0, 0)),
            pl.BlockSpec((1, bs, nh, dh),
                         lambda s, b, tbl, pos: (tbl[s, b], 0, 0, 0)),
            pl.BlockSpec((1, bs, nh, dh),
                         lambda s, b, tbl, pos: (tbl[s, b], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, nh, dh), lambda s, b, tbl, pos: (s, 0, 0)),
        scratch_shapes=softmax_scratch(nh, dh),  # fp32 acc/m/l in VMEM
    )
    kernel = functools.partial(
        _paged_kernel, block_size=bs, scale=1.0 / (dh ** 0.5))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, nh, dh), q.dtype),
        cost_estimate=pl.CostEstimate(
            # Worst case: every table entry live. 2 matmuls over the lane.
            flops=int(4 * slots * mb * bs * nh * dh),
            bytes_accessed=int(
                2 * slots * mb * bs * nh * dh * k_pool.dtype.itemsize),
            transcendentals=int(slots * mb * bs * nh),
        ),
        interpret=interpret,
    )(block_tables, positions, q, k_pool, v_pool)


def paged_decode_attention(q, k_pool, v_pool, block_tables, positions,
                           impl: str = "reference"):
    """Dispatch by `serving.attention_impl` ("pallas" | "reference")."""
    if impl == "pallas":
        return paged_attention_pallas(q, k_pool, v_pool, block_tables,
                                      positions)
    if impl == "reference":
        return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                         positions)
    raise ValueError(f"unknown paged attention impl {impl!r}")
