"""Ulysses-style sequence parallelism for attention (DeepSpeed-Ulysses,
arXiv:2309.14509 — re-derived for JAX shard_map; the reference has no
sequence parallelism at all, SURVEY.md §2.4/§5).

Attention needs every key/value for each query, so a sequence-sharded
layout cannot compute it locally. Ulysses swaps the sharded dimension with
two all-to-alls instead of gathering:

    [B, S/cp, H, Dh]  --all_to_all-->  [B, S, H/cp, Dh]   (shard heads)
        attention over the FULL sequence on H/cp local heads
    [B, S, H/cp, Dh]  --all_to_all-->  [B, S/cp, H, Dh]   (shard seq again)

Communication is 2 all-to-alls of the activation size — O(S·H·Dh/cp) per
chip — versus an all-gather of the whole K/V for the naive approach, and
unlike ring attention it composes with any inner attention kernel (the
full-sequence attention below can itself be the pallas flash kernel).

Used under `shard_map` over the mesh's `context` axis; wired into GPT-2
via `Config.attention_impl = "ulysses"`.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from determined_tpu import _jax_compat

_jax_compat.install()  # jax.sharding.get_abstract_mesh on jax < 0.5


def _inner_attention(q, k, v, causal: bool):
    """[B, S, H, Dh] full-sequence attention (XLA path)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), jnp.bool_))
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ulysses_attention(
    q: jax.Array,  # [B, S, H, Dh], sequence sharded over `seq_axis`
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    seq_axis: str = "context",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
    inner: str = "flash",  # full-seq kernel inside the shard: flash | dot
) -> jax.Array:
    """Attention with the sequence dim sharded over `seq_axis` via two
    all-to-alls (head-sharding inside). Falls back to plain attention when
    the ambient mesh has no (or a size-1) `seq_axis`."""
    mesh = jax.sharding.get_abstract_mesh()
    cp = (mesh.shape.get(seq_axis, 1) or 1) if mesh is not None else 1
    if cp <= 1:
        return _inner_attention(q, k, v, causal)

    # Inside the shard_map below the head dim is already sharded over
    # `head_axis`, so the all_to_all (split_axis=2) splits the LOCAL head
    # count — that, not the global count, must divide the context size.
    n_head = q.shape[2]
    tp = mesh.shape.get(head_axis, 1) or 1
    if n_head % tp != 0:
        raise ValueError(
            f"ulysses attention needs n_head ({n_head}) divisible by the "
            f"{head_axis} axis size ({tp})"
        )
    local_heads = n_head // tp
    if local_heads % cp != 0:
        raise ValueError(
            f"ulysses attention needs per-shard head count {local_heads} "
            f"(n_head {n_head} / {head_axis} size {tp}) divisible by the "
            f"{seq_axis} axis size ({cp})"
        )

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axes, seq_axis, head_axis, None)

    # This is Ulysses' composability advantage over ring attention: after
    # the all-to-all the shard holds the FULL sequence for a head subset,
    # so any single-device attention kernel drops in — including the
    # pallas flash kernel (which falls back to the XLA path off-TPU).
    if inner == "flash":
        from determined_tpu.ops.flash_attention import flash_attention

        def attend(qq, kk, vv):
            return flash_attention(qq, kk, vv, causal=causal)
    else:
        def attend(qq, kk, vv):
            return _inner_attention(qq, kk, vv, causal)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def sharded(ql, kl, vl):
        # local [b, S/cp, h, Dh] → [b, S, h/cp, Dh]: exchange seq chunks
        # for head chunks across the context group.
        def spread(x):
            return jax.lax.all_to_all(
                x, seq_axis, split_axis=2, concat_axis=1, tiled=True)

        def gather_back(x):
            return jax.lax.all_to_all(
                x, seq_axis, split_axis=1, concat_axis=2, tiled=True)

        out = attend(spread(ql), spread(kl), spread(vl))
        return gather_back(out)

    return sharded(q, k, v)
