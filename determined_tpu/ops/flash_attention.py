"""Fused causal attention.

The MFU-critical op for the GPT-2 north star (BASELINE.md). Strategy:
  - On TPU, use the pallas fused kernel (determined_tpu.ops.pallas_attention)
    when the shapes tile cleanly onto the MXU/VMEM.
  - Otherwise (CPU meshes, odd shapes) fall back to a numerically identical
    XLA implementation — jnp softmax(QK^T)V with fp32 accumulation. XLA
    already fuses the mask+softmax chain; the pallas kernel's win is avoiding
    the S×S logits round-trip to HBM.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, causal: bool) -> jax.Array:
    """Reference implementation. q,k,v: [B, S, H, D] → [B, S, H, D]."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_), k=s_k - s_q)
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _pallas_supported(q) -> bool:
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    b, s, h, d = q.shape
    return s % 128 == 0 and d in (64, 128, 256)


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
) -> jax.Array:
    if _pallas_supported(q):
        from determined_tpu.ops.pallas_attention import pallas_flash_attention

        return pallas_flash_attention(q, k, v, causal=causal)
    return _xla_attention(q, k, v, causal)


