"""Flash attention for TRAINING: Pallas fwd+bwd kernel, reference path,
and the `optimizations.attention_impl` dispatcher.

The MFU-critical op for the GPT-2 north star (ROADMAP item 5: 50.5% →
60%+ MFU). Three interchangeable implementations, selected by the
experiment config's `optimizations.attention_impl` block (threaded
through `gpt2.Config.attention_impl`; docs/training-perf.md):

  - `pallas` — the TPU kernel below. Tiled causal attention with online
    softmax: the S×S logits matrix never round-trips through HBM — each
    [block_q, block_k] tile lives in VMEM, the fp32 running max `m`,
    normalizer `l`, and accumulator `acc` sit in VMEM *scratch* across
    the k-tile grid dimension (`ops/_pallas_common.py`, the exact
    machinery of the serving decode kernel `ops/paged_attention.py`),
    and only the [S, D] output plus a per-row logsumexp (for the
    backward) are written back. Causal block skipping: tiles strictly
    above the diagonal are `pl.when`-predicated out AND their K/V
    BlockSpec index clamps to the causal frontier, so a skipped tile
    costs neither FLOPs nor a fresh DMA (consecutive programs with the
    same block index skip the re-fetch). Backward is the standard
    two-kernel flash split — dq grids over q tiles, dk/dv over k tiles —
    with p = exp(s - L) recomputed from the saved logsumexp and
    delta = rowsum(dO ∘ O) precomputed in XLA. Off-TPU the same kernels
    run through the pallas interpreter (tier-1 proves fwd AND bwd on the
    CPU mesh).

  - `reference` — pure-jnp with exactly the dense-attention arithmetic
    (fp32 logits, causal mask, fp32 softmax). Differentiable by plain
    `jax.grad`; tests/test_ops.py asserts the pallas backward against
    it. The `auto` fallback anywhere Pallas can't run.

  - `dense` — the legacy XLA path, byte-for-byte the pre-flash
    `_xla_attention` (kept as the A/B baseline for `make bench-train`).

The bf16 option (`bf16=True` / `optimizations.attention_bf16`): the
probability tile is cast to bfloat16 for the P·V (and dS·K / dS^T·Q)
matmuls so they ride the MXU's bf16 path; the QK^T products and the
online-softmax statistics m/l/acc always accumulate in fp32 — the one
place bf16 is never acceptable (exp/sum cancellation). The bf16 numerics
gate lives in tests/test_train_perf.py (loss-trajectory parity vs f32).

Layout: kernels operate on [BH, S, D] (batch×heads flattened); the
public wrappers accept the model's [B, S, H, D] and transpose.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from determined_tpu.ops._pallas_common import (
    HAVE_PALLAS,
    NEG_INF,
    finish_softmax_scratch,
    init_softmax_scratch,
    interpret_default,
    online_softmax_update,
    pick_blocks,
    softmax_scratch,
)

if HAVE_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

TRAIN_ATTENTION_IMPLS = ("auto", "pallas", "reference", "dense")


def resolve_attention_impl(setting: Optional[str] = None) -> str:
    """`optimizations.attention_impl` → the concrete implementation.

    auto (the default) picks pallas on TPU and reference elsewhere; the
    legacy model-config spellings stay accepted ("flash" == auto,
    "dot" == dense) so pre-PR-18 configs keep their exact behavior.
    """
    s = setting or "auto"
    if s in ("auto", "flash"):
        return "pallas" if jax.default_backend() in ("tpu", "axon") \
            else "reference"
    if s == "dot":
        return "dense"
    if s not in ("pallas", "reference", "dense"):
        raise ValueError(
            f"attention_impl must be one of {TRAIN_ATTENTION_IMPLS} "
            f"(or legacy flash/dot), got {setting!r}")
    return s


# --------------------------------------------------------------------------
# reference / dense paths
# --------------------------------------------------------------------------


def reference_attention(q, k, v, causal: bool = True,
                        bf16: bool = False) -> jax.Array:
    """Pure-jnp attention with exactly the dense arithmetic.

    q,k,v: [B, S, H, D] → [B, S, H, D]. fp32 logits and softmax; with
    bf16=True the probabilities are cast to bfloat16 for the P·V matmul
    (the kernel's bf16 option, mirrored so pallas-vs-reference stays an
    apples-to-apples equivalence check in both modes).
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_), k=s_k - s_q)
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = probs.astype(jnp.bfloat16 if bf16 else q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def _xla_attention(q, k, v, causal: bool) -> jax.Array:
    """The legacy dense path (attention_impl: dense), unchanged — the
    `make bench-train` A/B baseline and the pre-PR-18 default."""
    return reference_attention(q, k, v, causal=causal, bf16=False)


def _pallas_supported(q) -> bool:
    """Shapes the TPU kernel tiles cleanly (MXU lanes want d ∈ 64..256,
    sequence divisible into 128-lane tiles); anything else falls back to
    the reference path."""
    b, s, h, d = q.shape
    return HAVE_PALLAS and s % 128 == 0 and d in (64, 128, 256)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, bf16):
    qi, ki = pl.program_id(1), pl.program_id(2)
    num_k = pl.num_programs(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        init_softmax_scratch(acc_ref, m_ref, l_ref)

    # Causal frontier: tiles strictly above the diagonal contribute
    # nothing. Their programs still run (the TPU grid is static) but the
    # body is predicated out and the BlockSpec index_map clamps their K/V
    # fetch to the frontier tile — no FLOPs, no fresh DMA.
    visible = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(visible)
    def _accumulate():
        q = q_ref[0]                       # [block_q, d]
        k_blk = k_ref[0]                   # [block_k, d]
        st = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                          # [block_q, block_k] fp32
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            st = jnp.where(rows >= cols, st, NEG_INF)
        # bf16 option: P·V in bf16 on the MXU; fp32 otherwise. The m/l
        # statistics inside the update are fp32 either way.
        v_blk = v_ref[0] if bf16 else v_ref[0].astype(jnp.float32)
        online_softmax_update(st, v_blk, acc_ref, m_ref, l_ref)

    @pl.when(ki == num_k - 1)
    def _finish():
        finish_softmax_scratch(o_ref, acc_ref, l_ref, idx=0)
        lse_ref[0] = m_ref[...] + jnp.log(l_ref[...])  # [block_q, 1]


def _causal_k_index(block_q: int, block_k: int):
    """K/V index_map for q-major grids: clamp the k tile to the causal
    frontier so skipped programs re-request the tile they already hold
    (pallas skips the DMA when consecutive block indices repeat)."""

    def index_map(b, i, j):
        return (b, jnp.minimum(j, (i * block_q + block_q - 1) // block_k), 0)

    return index_map


def _causal_q_index(block_q: int, block_k: int):
    """Q-side index_map for k-major grids (the dk/dv kernel): clamp the q
    tile up to the first visible row block."""

    def index_map(b, j, i):
        return (b, jnp.maximum(i, (j * block_k) // block_q), 0)

    return index_map


def _flash_fwd(q, k, v, causal: bool, bf16: bool, interpret):
    """q,k,v: [BH, S, D] → (o [BH,S,D], lse [BH,S,1] fp32)."""
    bh, s, d = q.shape
    block_q, block_k = pick_blocks(s)
    scale = 1.0 / math.sqrt(d)
    grid = (bh, s // block_q, s // block_k)
    kv_index = (_causal_k_index(block_q, block_k) if causal
                else (lambda b, i, j: (b, j, 0)))
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bf16=bf16)
    flops_per_bh = 4 * s * s * d * (0.5 if causal else 1.0)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # trailing unit dim: TPU block tiling needs the last dim to match
            # the array (per-row stats can't be a bare [bh, s] block)
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=softmax_scratch(block_q, d),  # fp32 acc/m/l in VMEM
        cost_estimate=pl.CostEstimate(
            flops=int(flops_per_bh * bh),
            bytes_accessed=int(3 * bh * s * d * q.dtype.itemsize),
            transcendentals=int(bh * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc_ref, *, scale, causal, bf16):
    qi, ki = pl.program_id(1), pl.program_id(2)
    num_k = pl.num_programs(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    visible = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(visible)
    def _accumulate():
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]       # [block_q, 1]
        delta = delta_ref[0]   # [block_q, 1]
        st = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(st - lse)  # ≤ 1; lse is the exact logsumexp
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(
            jnp.bfloat16 if bf16 else jnp.float32)
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds, k_blk.astype(ds.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc_ref, dv_acc_ref, *, scale, causal, bf16):
    ki, qi = pl.program_id(1), pl.program_id(2)
    num_q = pl.num_programs(2)
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # Mirror image of the forward frontier: q tiles strictly above the
    # diagonal see nothing of this k tile.
    visible = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(visible)
    def _accumulate():
        k_blk = k_ref[0]       # [block_k, d]
        v_blk = v_ref[0]
        q_blk = q_ref[0]       # [block_q, d]
        do = do_ref[0]
        lse = lse_ref[0]       # [block_q, 1]
        delta = delta_ref[0]
        st = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale              # [block_q, block_k]
        p = jnp.exp(st - lse)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        pt = p.astype(jnp.bfloat16 if bf16 else jnp.float32)
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            pt, do.astype(pt.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, d]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = (p * (dp - delta) * scale).astype(pt.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds, q_blk.astype(ds.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, d]

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal: bool, bf16: bool, interpret):
    bh, s, d = q.shape
    block_q, block_k = pick_blocks(s)
    scale = 1.0 / math.sqrt(d)
    # delta_i = sum_d dO_id * O_id — cheap elementwise reduce; let XLA fuse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [bh, s, 1]
    q_major = lambda b, i, j: (b, i, 0)  # noqa: E731 — index_map shorthand
    kv_index = (_causal_k_index(block_q, block_k) if causal
                else (lambda b, i, j: (b, j, 0)))
    bwd_flops = 10 * s * s * d * (0.5 if causal else 1.0)  # 5 matmuls

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bf16=bf16),
        grid=(bh, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_major),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_q, d), q_major),
            pl.BlockSpec((1, block_q, 1), q_major),
            pl.BlockSpec((1, block_q, 1), q_major),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_major),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(bwd_flops * bh * 0.4),
            bytes_accessed=int(4 * bh * s * d * q.dtype.itemsize),
            transcendentals=int(bh * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    k_major = lambda b, j, i: (b, j, 0)  # noqa: E731
    q_index = (_causal_q_index(block_q, block_k) if causal
               else (lambda b, j, i: (b, i, 0)))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bf16=bf16),
        grid=(bh, s // block_k, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), k_major),
            pl.BlockSpec((1, block_k, d), k_major),
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, 1), q_index),
            pl.BlockSpec((1, block_q, 1), q_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), k_major),
            pl.BlockSpec((1, block_k, d), k_major),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(bwd_flops * bh * 0.6),
            bytes_accessed=int(4 * bh * s * d * q.dtype.itemsize),
            transcendentals=int(bh * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public op with custom vjp
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, bf16, interpret):
    o, _ = _flash_fwd(q, k, v, causal, bf16, interpret)
    return o


def _flash_vjp_fwd(q, k, v, causal, bf16, interpret):
    o, lse = _flash_fwd(q, k, v, causal, bf16, interpret)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, bf16, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, causal, bf16, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def pallas_flash_attention(q, k, v, causal: bool = True, bf16: bool = False,
                           interpret: Optional[bool] = None) -> jax.Array:
    """q,k,v: [B, S, H, D] → [B, S, H, D]. Fused training attention
    (differentiable; the custom vjp runs the two-kernel flash backward)."""
    if not HAVE_PALLAS:
        raise RuntimeError(
            "pallas unavailable in this jax build; use "
            "optimizations.attention_impl: reference")
    if interpret is None:
        interpret = interpret_default()
    b, s, h, d = q.shape
    to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)  # noqa: E731
    o = _flash(to3(q), to3(k), to3(v), causal, bf16, interpret)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    impl: Optional[str] = None,
    bf16: bool = False,
) -> jax.Array:
    """Causal self-attention, dispatched by `optimizations.attention_impl`.

    impl: auto | pallas | reference | dense (None == auto; legacy
    flash/dot accepted). An explicit `pallas` on shapes the kernel can't
    tile falls back to the reference path — same arithmetic contract,
    asserted by tests/test_ops.py.
    """
    resolved = resolve_attention_impl(impl)
    if resolved == "pallas" and _pallas_supported(q):
        return pallas_flash_attention(q, k, v, causal=causal, bf16=bf16)
    if resolved == "dense":
        return _xla_attention(q, k, v, causal)
    return reference_attention(q, k, v, causal=causal, bf16=bf16)
