"""Ring attention — causal self-attention with the sequence sharded over the
`context` mesh axis.

First-class context parallelism (absent in the reference, SURVEY.md §2.4/§5):
each device holds S/n of the sequence; K/V blocks rotate around the ICI ring
via `ppermute` while every device accumulates flash-style (running max m,
normaliser l, weighted output o) against its local Q block. Communication
overlaps with the block matmuls and total memory is O(S/n) per device —
sequence length scales linearly with ring size.

Layout contract: q,k,v are [B, S, H, D] sharded P(batch, "context", heads, -)
outside; inside shard_map each device sees [B, S/n, H, D].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from determined_tpu import _jax_compat

_jax_compat.install()  # jax.sharding.get_abstract_mesh on jax < 0.5

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_attend(q, k, v, mask, sm_scale):
    """One q-block × kv-block flash partial: returns (m, l, o) in fp32.

    q: [B,Sq,H,D], k/v: [B,Sk,H,D], mask: [Sq,Sk] bool or None.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)  # [B,H,Sq]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    m_safe = jnp.where(m == NEG_INF, 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_safe, l, o


def _combine(m1, l1, o1, m2, l2, o2):
    """Merge two flash partials with the standard rescaling identity."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    # a*: [B,H,Sq] → broadcast onto o: [B,Sq,H,D]
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[..., None]
    return m, l, o


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Runs inside shard_map; q,k,v are the device-local blocks."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_q, h, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)

    causal_mask = jnp.tril(jnp.ones((s_q, s_q), jnp.bool_)) if causal else None

    m0 = jnp.full((b, h, s_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_q), jnp.float32)
    o0 = jnp.zeros((b, s_q, h, d), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        m, l, o, k_cur, v_cur = carry
        kv_idx = (my_idx - step) % axis_size
        # Block-level causality: kv block strictly before ours → unmasked;
        # our own block → triangular; after ours → skipped entirely.
        def attend(mask):
            bm, bl, bo = _block_attend(q, k_cur, v_cur, mask, sm_scale)
            return _combine(m, l, o, bm, bl, bo)

        if causal:
            m2, l2, o2 = jax.lax.cond(
                kv_idx < my_idx,
                lambda: attend(None),
                lambda: jax.lax.cond(
                    kv_idx == my_idx,
                    lambda: attend(causal_mask),
                    lambda: (m, l, o),
                ),
            )
        else:
            m2, l2, o2 = attend(None)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return m2, l2, o2, k_next, v_next

    m, l, o, _, _ = jax.lax.fori_loop(0, axis_size, body, (m0, l0, o0, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (can't happen causal)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D], S sharded over `axis_name`
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "context",
    causal: bool = True,
    mesh=None,
) -> jax.Array:
    """Causal ring attention over the ambient mesh's `axis_name` ring.

    Falls back to single-block fused attention when the axis has size 1
    (including CPU test meshes with context=1).
    """
    if mesh is None:
        # Works both inside jit (abstract mesh from the ambient set_mesh) and
        # outside (set_mesh also installs the abstract mesh).
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or mesh.empty:
            from determined_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal)
    if mesh.shape.get(axis_name, 1) == 1:
        from determined_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    head_axis = "tensor" if "tensor" in mesh.axis_names else None
    spec = P(batch_axes or None, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
