"""TPU kernels: fused attention (pallas) + ring attention (context parallel).

The compute-hot ops the framework owns directly rather than leaving to XLA's
default lowering. Everything here has a pure-XLA fallback so the same model
code runs on CPU test meshes.
"""

from determined_tpu.ops.flash_attention import flash_attention  # noqa: F401
from determined_tpu.ops.paged_attention import (  # noqa: F401
    paged_attention_pallas,
    paged_attention_reference,
    paged_decode_attention,
)
from determined_tpu.ops.ring_attention import ring_attention  # noqa: F401
