"""Shared plumbing for the platform's Pallas TPU attention kernels.

Both attention kernels — the serving decode kernel
(`ops/paged_attention.py`, PR 11) and the training flash kernel
(`ops/flash_attention.py`) — are online-softmax accumulators walking a
grid of K/V tiles: fp32 running max `m`, normalizer `l`, and output
accumulator `acc` live in VMEM scratch across the innermost grid
dimension, initialized at the first tile and normalized out at the last.
This module is the single home for that machinery so the two kernels
cannot drift (the decode kernel once carried its own private copies):

  - availability / interpret-mode policy (`HAVE_PALLAS`,
    `interpret_default`): tier-1 runs every kernel on CPU through the
    pallas interpreter, real TPUs compile the same code via Mosaic;
  - grid sizing (`pick_blocks`): MXU/VMEM-friendly tile edges that
    divide the sequence;
  - VMEM scratch shapes for the online-softmax state
    (`softmax_scratch`);
  - the accumulate step itself (`online_softmax_update`): one masked
    logits tile folded into (acc, m, l) — written once, used by decode
    and by the training forward kernel.

Keep this module import-safe without pallas: the serving reference path
and CPU-only deploys must not pay a hard pallas dependency.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

try:  # pallas is optional at import time
    from jax.experimental import pallas as pl  # noqa: F401
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover - pallas not in this build
    HAVE_PALLAS = False

# Masked logits value. Not -inf: exp(-inf - -inf) is NaN when an entire
# row is masked (the first causal tile's padding rows); a large-negative
# finite value keeps exp() at exactly 0.0 without poisoning m.
NEG_INF = -1e30


def interpret_default() -> bool:
    """Pallas TPU kernels run interpreted off-TPU (tier-1 on CPU)."""
    return jax.default_backend() != "tpu"


def pick_blocks(s: int, max_block: int = 512) -> Tuple[int, int]:
    """(block_q, block_k) tile edges tuned for v5e VMEM; both divide s.

    512 keeps the fp32 logits tile (512x512x4B = 1 MiB) plus the q/k/v/o
    tiles comfortably inside the ~16 MiB VMEM budget with room for the
    pipeline's double buffering; shorter sequences halve down until the
    edge divides s.
    """
    block_q = min(max_block, s)
    block_k = min(max_block, s)
    while s % block_q:
        block_q //= 2
    while s % block_k:
        block_k //= 2
    return block_q, block_k


def softmax_scratch(rows: int, d: int):
    """VMEM scratch for one online-softmax accumulator: [acc, m, l].

    `rows` is the per-program row count (query rows for the training
    kernel, heads for the decode kernel); `d` the output feature depth.
    All three are fp32 regardless of the i/o dtype — the running
    statistics are the one place bf16 is never acceptable (exp/sum
    cancellation), which is also why they live in dedicated scratch
    rather than riding the (possibly bf16) output block.
    """
    if not HAVE_PALLAS:  # pragma: no cover - guarded by callers
        raise RuntimeError("pallas unavailable in this jax build")
    return [
        pltpu.VMEM((rows, d), jnp.float32),  # acc
        pltpu.VMEM((rows, 1), jnp.float32),  # running max m
        pltpu.VMEM((rows, 1), jnp.float32),  # running normalizer l
    ]


def init_softmax_scratch(acc_ref, m_ref, l_ref) -> None:
    """Reset (acc, m, l) at the first tile of a program's accumulation."""
    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)


def online_softmax_update(st, v, acc_ref, m_ref, l_ref,
                          dimension_numbers=(((1,), (0,)), ((), ()))):
    """Fold one masked logits tile into the VMEM (acc, m, l) state.

    st: fp32 logits tile [rows, cols] with masked entries at NEG_INF;
    v:  the matching value tile, contracted with the tile's probabilities
        per `dimension_numbers` (default: plain [cols, d] matmul).

    The p·v matmul runs in the value dtype (bf16 inputs hit the MXU's
    bf16 path) but accumulates into fp32 (`preferred_element_type`) —
    the split the online-softmax statistics demand: m/l/acc stay exact
    while the O(s²·d) multiply rides the fast path.
    """
    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(st, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(st - m_new)
    m_ref[...] = m_new
    l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, dimension_numbers,
        preferred_element_type=jnp.float32)


def finish_softmax_scratch(o_ref, acc_ref, l_ref, idx=...) -> None:
    """Normalize the accumulator out to the output block's dtype.

    `idx` addresses the output block when it carries a leading unit dim
    (the decode kernel's (1, H, Dh) slot block passes idx=0)."""
    o_ref[idx] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)
