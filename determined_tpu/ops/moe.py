"""Mixture-of-Experts block with expert parallelism over the mesh's
`expert` axis.

The reference has no MoE/expert-parallel support at all (SURVEY.md §2.4:
"expert parallel — absent"); the TPU build makes it first-class per the
§2.4 TPU mapping ("shard_map for EP/Ulysses"). Design follows the
GShard/Switch dispatch formulation re-derived for GSPMD:

  - top-k router with capacity factor; overflow tokens are dropped (their
    combine weight is zero, so the residual path carries them — standard
    Switch behaviour);
  - dispatch/combine are dense one-hot einsums: `xe = d[t,e,c] · x[t,d]`
    gives per-expert token buffers [E, C, D] which GSPMD shards over the
    `expert` mesh axis (the einsum boundary becomes the all-to-all); the
    expert FFN itself is a batched matmul with weights sharded [E→expert];
  - an auxiliary load-balancing loss (mean fraction × mean router prob ×
    E²) keeps the router from collapsing onto one expert.

Everything is expressed with logical-axis sharding constraints
(parallel/sharding.py) so the same code runs replicated on one chip and
expert-parallel on a mesh with `expert > 1`.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from determined_tpu.parallel.sharding import LogicalRules, shard_logical


def init_moe(
    rng: jax.Array,
    d_model: int,
    d_ff: int,
    num_experts: int,
    param_dtype=jnp.float32,
    std: float = 0.02,
    layers: Optional[int] = None,
) -> Dict[str, Any]:
    """Parameters for one MoE FFN (or a stacked [layers, ...] pytree)."""
    lead = () if layers is None else (layers,)
    k_router, k_up, k_down = jax.random.split(rng, 3)

    def normal(k, shape, s):
        return (jax.random.normal(k, lead + shape) * s).astype(param_dtype)

    return {
        "router": {"kernel": normal(k_router, (d_model, num_experts), std)},
        "up": {
            "kernel": normal(k_up, (num_experts, d_model, d_ff), std),
            "bias": jnp.zeros(lead + (num_experts, d_ff), param_dtype),
        },
        "down": {
            "kernel": normal(
                k_down, (num_experts, d_ff, d_model), std / math.sqrt(2)
            ),
            "bias": jnp.zeros(lead + (num_experts, d_model), param_dtype),
        },
    }


def moe_logical_axes(layers: bool = False) -> Dict[str, Any]:
    """Logical axis names for init_moe params (expert dim → `expert` mesh
    axis via the default rules)."""
    L = ("layers",) if layers else ()
    return {
        "router": {"kernel": L + ("embed", None)},
        "up": {"kernel": L + ("expert", "embed", "mlp"),
               "bias": L + ("expert", "mlp")},
        "down": {"kernel": L + ("expert", "mlp", "embed"),
                 "bias": L + ("expert", "embed")},
    }


def moe_block(
    x: jax.Array,  # [B, S, D]
    params: Dict[str, Any],
    num_experts: int,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    rules: Optional[LogicalRules] = None,
) -> Tuple[jax.Array, jax.Array]:
    """→ (y [B, S, D], aux_load_balance_loss scalar f32)."""
    b, s, d = x.shape
    t = b * s
    e = num_experts
    k = min(top_k, e)
    dt = x.dtype
    xt = x.reshape(t, d)

    # Router in f32 (small matmul; numerics matter for the softmax).
    logits = (xt.astype(jnp.float32)
              @ params["router"]["kernel"].astype(jnp.float32))  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # Top-k expert choice per token.
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [T, k]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    capacity = max(1, int(math.ceil(t / e * capacity_factor)))

    # GShard-style position assignment: for each of the k choices in
    # priority order, a token takes the next free slot in its expert's
    # buffer; tokens past capacity are dropped (combine weight 0).
    dispatch = jnp.zeros((t, e, capacity), dtype=dt)
    combine = jnp.zeros((t, e, capacity), dtype=dt)
    used = jnp.zeros((e,), jnp.int32)  # slots consumed per expert so far
    for choice in range(k):
        sel = jax.nn.one_hot(gate_idx[:, choice], e, dtype=jnp.int32)  # [T,E]
        pos = jnp.cumsum(sel, axis=0) - 1 + used[None, :]  # [T, E]
        within = (pos < capacity) & (sel > 0)
        pos_c = jnp.clip(pos, 0, capacity - 1)
        oh = jax.nn.one_hot(pos_c, capacity, dtype=dt) * within[..., None]
        dispatch = dispatch + oh
        combine = combine + oh * gate_vals[:, choice, None, None].astype(dt)
        used = used + jnp.sum(sel, axis=0)

    # Per-expert token buffers; the [E, ...] dims shard over `expert`, so
    # XLA places each expert's buffer (and its FFN) on its own sub-mesh and
    # inserts the all-to-all at the einsum boundary.
    xe = jnp.einsum("tec,td->ecd", dispatch, xt)
    xe = shard_logical(xe, ("expert", None, "embed"), rules)
    h = jnp.einsum("ecd,edf->ecf", xe, params["up"]["kernel"].astype(dt))
    h = h + params["up"]["bias"].astype(dt)[:, None, :]
    h = shard_logical(h, ("expert", None, "mlp"), rules)
    h = jax.nn.gelu(h, approximate=True)
    ye = jnp.einsum("ecf,efd->ecd", h, params["down"]["kernel"].astype(dt))
    ye = ye + params["down"]["bias"].astype(dt)[:, None, :]
    ye = shard_logical(ye, ("expert", None, "embed"), rules)
    y = jnp.einsum("tec,ecd->td", combine, ye)

    # Load-balance aux (Switch Transformer eq. 4): E · Σ_e f_e · p_e where
    # f_e = fraction of tokens routed (first choice) to e, p_e = mean
    # router prob for e. Minimised at uniform routing.
    first = jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32)
    f = jnp.mean(first, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p)

    return y.reshape(b, s, d), aux
