"""GPT-2 in plain JAX, TPU-first.

The flagship workload (BASELINE.md north star: GPT-2 pretraining ≥40% MFU).
Equivalent capability to the reference's HF-Trainer GPT-2 path
(reference: examples/hf_trainer_api/hf_language_modeling/run_clm.py,
harness/determined/transformers/_hf_callback.py) but re-designed for the MXU:

  - bfloat16 activations, fp32 params/optimizer (mixed precision by default)
  - transformer blocks stacked along a leading "layers" dim and iterated with
    `lax.scan` → one compiled block regardless of depth
  - logical-axis sharding annotations (batch/embed/heads/mlp/vocab) so the
    same model runs DP, FSDP, TP or any combination by swapping rules
  - optional `jax.checkpoint` rematerialisation of each block
  - attention pluggable via `optimizations.attention_impl`
    (auto | pallas | reference | dense — ops/flash_attention.py; plus the
    context-parallel "ring"/"ulysses" strategies) with an optional
    bf16-probabilities mode (`attention_bf16`)
  - optional comm/compute overlap (`overlap_allgather`): the layers scan
    carries the current layer's fsdp-gathered params while the next
    layer's all-gather is issued a step ahead (docs/training-perf.md)
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from determined_tpu.parallel.sharding import LogicalRules, shard_logical


@dataclasses.dataclass(frozen=True)
class Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    d_model: int = 768
    n_layer: int = 12
    n_head: int = 12
    d_ff: int = 0  # 0 → 4*d_model
    dropout: float = 0.0  # pretraining default; rng-free when 0
    dtype: Any = jnp.bfloat16  # activation dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # jax.checkpoint policy name: None = full remat; "dots" saves matmul
    # outputs and recomputes only elementwise/softmax (less recompute, more
    # HBM); see jax.checkpoint_policies.
    remat_policy: Optional[str] = "dots"
    # `optimizations.attention_impl`: "auto" = pallas flash kernel on TPU,
    # jnp reference elsewhere; "pallas"/"reference" force one side;
    # "dense" = legacy XLA path (A/B baseline). Legacy spellings accepted:
    # "flash" == auto, "dot" == dense. "ring"/"ulysses" = context-parallel.
    attention_impl: str = "flash"
    # `optimizations.attention_bf16`: cast attention probabilities to bf16
    # for the P·V / dS·K matmuls (MXU bf16 path); the online-softmax
    # statistics stay fp32 regardless. Numerics gate: tests/test_models.py.
    attention_bf16: bool = False
    # `optimizations.overlap_allgather`: restructure the layers scan so each
    # layer's fsdp param all-gather is issued one layer ahead of its use
    # (carry holds the gathered slice; gather overlaps the previous layer's
    # compute). No-op unless the rules map params onto a >1 "fsdp" axis.
    overlap_allgather: bool = False
    layer_norm_eps: float = 1e-5
    # Unroll factor for the layers scan. 0 = full unroll: removes the
    # per-layer stacked-param dynamic-slice and scan-carry stacking overhead
    # (~10% step time on v5e) at the cost of longer compiles; 1 = rolled
    # (fast compile — the right default for tests and short ASHA trials).
    scan_unroll: int = 1
    # Mixture-of-Experts: >1 replaces every block's MLP with a top-k routed
    # MoE FFN whose experts shard over the mesh `expert` axis (ops/moe.py).
    # The reference has no MoE at all (SURVEY §2.4).
    num_experts: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    @staticmethod
    def small() -> "Config":
        return Config()  # gpt2-124M

    @staticmethod
    def medium() -> "Config":
        return Config(d_model=1024, n_layer=24, n_head=16)

    @staticmethod
    def large() -> "Config":
        return Config(d_model=1280, n_layer=36, n_head=20)

    @staticmethod
    def tiny() -> "Config":
        """Test-sized config (CPU-mesh unit tests, dryrun_multichip)."""
        return Config(
            vocab_size=512, n_positions=128, d_model=64, n_layer=2, n_head=4
        )


def flops_per_token(cfg: Config, seq_len: int) -> float:
    """Approx fwd+bwd FLOPs per token (6N + attention term) for MFU math."""
    n_params = param_count(cfg)
    attn = 12 * cfg.n_layer * cfg.d_model * seq_len  # 2*2*3 * L * d * s
    return 6.0 * n_params + attn


def param_count(cfg: Config) -> int:
    d, f, v, p, L = cfg.d_model, cfg.ff_dim, cfg.vocab_size, cfg.n_positions, cfg.n_layer
    per_layer = (
        3 * d * d + 3 * d  # qkv
        + d * d + d  # attn out
        + d * f + f  # mlp up
        + f * d + d  # mlp down
        + 4 * d  # 2 layernorms
    )
    return v * d + p * d + L * per_layer + 2 * d  # + final ln


# ---------------------------------------------------------------- init


def _normal(rng, shape, std, dtype):
    return (jax.random.normal(rng, shape) * std).astype(dtype)


def init(rng: jax.Array, cfg: Config) -> Dict[str, Any]:
    d, f, v, p, L = cfg.d_model, cfg.ff_dim, cfg.vocab_size, cfg.n_positions, cfg.n_layer
    pd = cfg.param_dtype
    keys = jax.random.split(rng, 8)
    # GPT-2 init: N(0, 0.02); residual projections scaled by 1/sqrt(2L).
    std, res_std = 0.02, 0.02 / math.sqrt(2 * L)

    def layer_params(k):
        ks = jax.random.split(k, 4)
        out = {
            "ln1": {"scale": jnp.ones((L, d), pd), "bias": jnp.zeros((L, d), pd)},
            "qkv": {
                "kernel": _normal(ks[0], (L, d, 3 * d), std, pd),
                "bias": jnp.zeros((L, 3 * d), pd),
            },
            "attn_out": {
                "kernel": _normal(ks[1], (L, d, d), res_std, pd),
                "bias": jnp.zeros((L, d), pd),
            },
            "ln2": {"scale": jnp.ones((L, d), pd), "bias": jnp.zeros((L, d), pd)},
        }
        if cfg.num_experts > 1:
            from determined_tpu.ops.moe import init_moe

            out["moe"] = init_moe(
                ks[2], d, f, cfg.num_experts, param_dtype=pd, std=std,
                layers=L,
            )
        else:
            out["mlp_up"] = {
                "kernel": _normal(ks[2], (L, d, f), std, pd),
                "bias": jnp.zeros((L, f), pd),
            }
            out["mlp_down"] = {
                "kernel": _normal(ks[3], (L, f, d), res_std, pd),
                "bias": jnp.zeros((L, d), pd),
            }
        return out

    return {
        "wte": _normal(keys[0], (v, d), std, pd),
        "wpe": _normal(keys[1], (p, d), std, pd),
        "blocks": layer_params(keys[2]),
        "ln_f": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
    }


def param_logical_axes(cfg: Config) -> Dict[str, Any]:
    """Logical axis names per param dim; the leading "layers" dim of the
    stacked blocks shards over the `pipeline` mesh axis (replicated when
    pipeline=1)."""
    L = "layers"
    blocks: Dict[str, Any] = {
        "ln1": {"scale": (L, "embed"), "bias": (L, "embed")},
        "qkv": {"kernel": (L, "embed", "heads"), "bias": (L, "heads")},
        "attn_out": {"kernel": (L, "heads", "embed"), "bias": (L, "embed")},
        "ln2": {"scale": (L, "embed"), "bias": (L, "embed")},
    }
    if cfg.num_experts > 1:
        blocks["moe"] = {
            "router": {"kernel": (L, "embed", None)},
            "up": {"kernel": (L, "expert", "embed", "mlp"),
                   "bias": (L, "expert", "mlp")},
            "down": {"kernel": (L, "expert", "mlp", "embed"),
                     "bias": (L, "expert", "embed")},
        }
    else:
        blocks["mlp_up"] = {"kernel": (L, "embed", "mlp"), "bias": (L, "mlp")}
        blocks["mlp_down"] = {"kernel": (L, "mlp", "embed"),
                              "bias": (L, "embed")}
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": blocks,
        "ln_f": {"scale": ("embed",), "bias": ("embed",)},
    }


# ---------------------------------------------------------------- forward


def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _attention(q, k, v, cfg: Config, rules: Optional[LogicalRules]):
    """q,k,v: [B, S, H, Dh]. Causal self-attention."""
    if cfg.attention_impl == "ring":
        from determined_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, axis_name="context")
    if cfg.attention_impl == "ulysses":
        from determined_tpu.ops.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=True)
    from determined_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=True, impl=cfg.attention_impl,
                           bf16=cfg.attention_bf16)


def _fsdp_stripped_entry(entry):
    """One PartitionSpec entry with the fsdp mesh axis removed."""
    if entry is None:
        return None
    if isinstance(entry, (tuple, list)):
        kept = tuple(a for a in entry if a != "fsdp")
        # len() of a Python axis-name tuple, not a traced shape.
        return kept[0] if len(kept) == 1 else (kept or None)  # det: noqa[DTL104]
    return None if entry == "fsdp" else entry


def _gather_block_params(lp, cfg: Config, rules: LogicalRules):
    """Constrain one layer's param slice to its fsdp-UNsharded layout.

    Each leaf keeps every mesh axis its logical spec resolves to except
    "fsdp" — i.e. tensor-parallel shards stay sharded, only the fsdp
    split is gathered. Placing this constraint where the slice enters the
    scan carry is what lets the partitioner issue layer N+1's all-gather
    while layer N's matmuls run (`overlap_allgather`)."""
    axes = param_logical_axes(cfg)["blocks"]

    def one(p, leaf_axes):
        spec = rules.spec(tuple(leaf_axes)[1:])  # drop stacked layers dim
        stripped = jax.sharding.PartitionSpec(
            *[_fsdp_stripped_entry(e) for e in spec])
        try:
            return jax.lax.with_sharding_constraint(p, stripped)
        except (ValueError, RuntimeError):  # no mesh context (eager use)
            return p

    return jax.tree.map(one, lp, axes)


def _scan_overlap(block, x, blocks, cfg: Config, rules: LogicalRules,
                  unroll: int):
    """Layers scan with the fsdp all-gather issued one layer ahead.

    The carry holds the CURRENT layer's already-gathered params; xs are
    the block stack rolled by −1 so iteration i delivers layer i+1's
    shards. The body constrains the incoming slice to the fsdp-stripped
    spec BEFORE running the current block, so the gather collective for
    the next layer overlaps this layer's compute instead of serializing
    in front of it. Arithmetic is identical to the plain scan (asserted
    in tests/test_models.py); the final iteration's rolled-around gather
    of layer 0 is dead and DCE'd or wasted-but-harmless.
    """
    first = jax.tree.map(lambda p: p[0], blocks)
    rest = jax.tree.map(lambda p: jnp.roll(p, -1, axis=0), blocks)
    gathered0 = _gather_block_params(first, cfg, rules)

    def body(carry, lp_next):
        xx, lp = carry
        lp_next = _gather_block_params(lp_next, cfg, rules)
        xx, aux = block(xx, lp)
        return (xx, lp_next), aux

    (x, _), auxs = jax.lax.scan(body, (x, gathered0), rest, unroll=unroll)
    return x, auxs


def _block(x, lp, cfg: Config, rules: Optional[LogicalRules]):
    """One transformer block. x: [B, S, D]; lp: this layer's param slice."""
    b, s, d = x.shape
    h, dh = cfg.n_head, cfg.head_dim
    dt = cfg.dtype

    y = _layer_norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"], cfg.layer_norm_eps)
    qkv = jnp.einsum("bsd,de->bse", y, lp["qkv"]["kernel"].astype(dt)) + lp["qkv"][
        "bias"
    ].astype(dt)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, h, dh)
    k = k.reshape(b, s, h, dh)
    v = v.reshape(b, s, h, dh)
    q = shard_logical(q, ("batch", "seq", "heads", "kv"), rules)
    k = shard_logical(k, ("batch", "seq", "heads", "kv"), rules)
    attn = _attention(q, k, v, cfg, rules).reshape(b, s, d)
    attn = (
        jnp.einsum("bsd,de->bse", attn, lp["attn_out"]["kernel"].astype(dt))
        + lp["attn_out"]["bias"].astype(dt)
    )
    x = x + attn
    x = shard_logical(x, ("batch", "seq", "embed"), rules)

    y = _layer_norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"], cfg.layer_norm_eps)
    if cfg.num_experts > 1:
        from determined_tpu.ops.moe import moe_block

        down, aux = moe_block(
            y, lp["moe"], cfg.num_experts, top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor, rules=rules,
        )
    else:
        up = jnp.einsum("bsd,df->bsf", y, lp["mlp_up"]["kernel"].astype(dt)) + lp[
            "mlp_up"
        ]["bias"].astype(dt)
        up = shard_logical(up, ("batch", "seq", "mlp"), rules)
        up = jax.nn.gelu(up, approximate=True)
        down = (
            jnp.einsum("bsf,fd->bsd", up, lp["mlp_down"]["kernel"].astype(dt))
            + lp["mlp_down"]["bias"].astype(dt)
        )
        aux = jnp.zeros((), jnp.float32)
    x = x + down
    return shard_logical(x, ("batch", "seq", "embed"), rules), aux


def _remat(block, cfg: Config):
    """Wrap a block fn in jax.checkpoint per cfg.remat_policy."""
    policies = {
        None: None,
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "everything": jax.checkpoint_policies.everything_saveable,
    }
    policy = policies[cfg.remat_policy]
    return jax.checkpoint(block, policy=policy) if policy else jax.checkpoint(block)


def _nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean NLL without materialising a full fp32 log-softmax over the
    vocab: nll = logsumexp(logits) - logits[target]. XLA fuses the f32
    upcast into the reduction, so the [B,S,V] array stays bf16 in HBM."""
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt.astype(jnp.float32))


def _shift(batch: Dict[str, jax.Array]):
    tokens = batch["tokens"]
    if "targets" in batch:
        return tokens, batch["targets"]
    return tokens[:, :-1], tokens[:, 1:]


def _ambient_mesh():
    """The mesh in effect for the current trace, or None.

    jax >= 0.5 tracks it as the abstract mesh (set_mesh/use_mesh install
    it); on older jax only the physical `with Mesh(...)` context exists —
    fall back to it so the vocab-sharding decision below works on both.
    """
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is not None:
        mesh = get()
        if mesh is not None and not mesh.empty:
            return mesh
    try:
        from jax._src import mesh as mesh_lib

        phys = mesh_lib.thread_resources.env.physical_mesh
        if phys is not None and not phys.empty:
            return phys
    except Exception:
        pass
    return None


def _embed_tokens(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: Config,
    rules: Optional[LogicalRules],
    dtype,
) -> jax.Array:
    """Token embedding honoring vocab sharding (shared by apply() and
    apply_pipelined(), so pipeline+vocab-sharded configs don't regress).

    Megatron parallel embedding: with the table ACTUALLY vocab-sharded
    (rules map "vocab" to a >1 mesh axis), a gather forces SPMD into
    involuntary full rematerialization (all-gather the table AND
    replicate the output — the warnings VERDICT r4 weak #2 flags). A
    one-hot matmul instead contracts over vocab locally per shard + one
    psum, native on the MXU. Rules that keep wte replicated keep the
    near-free gather.
    """
    wte = params["wte"].astype(dtype)
    mesh = _ambient_mesh()
    vocab_axes = (rules or LogicalRules()).mesh_axes("vocab")
    if isinstance(vocab_axes, str):
        vocab_axes = (vocab_axes,)
    vocab_sharded = mesh is not None and any(
        (mesh.shape.get(a, 1) or 1) > 1 for a in (vocab_axes or ()))
    if vocab_sharded:
        return jax.nn.one_hot(tokens, cfg.vocab_size, dtype=dtype) @ wte
    return wte[tokens]


def apply(
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, S] int32
    cfg: Config,
    rules: Optional[LogicalRules] = None,
    return_aux: bool = False,
):
    """Forward pass → logits [B, S, vocab] (bf16); with return_aux also the
    mean MoE load-balance loss (0 for dense configs)."""
    b, s = tokens.shape
    dt = cfg.dtype
    x = _embed_tokens(params, tokens, cfg, rules, dt)
    x = x + params["wpe"].astype(dt)[:s][None]
    x = shard_logical(x, ("batch", "seq", "embed"), rules)

    block = partial(_block, cfg=cfg, rules=rules)
    if cfg.remat:
        block = _remat(block, cfg)

    def scan_body(carry, lp):
        x, aux = block(carry, lp)
        return x, aux

    unroll = cfg.scan_unroll if cfg.scan_unroll > 0 else cfg.n_layer
    if cfg.overlap_allgather and rules is not None:
        x, auxs = _scan_overlap(block, x, params["blocks"], cfg, rules,
                                unroll)
    else:
        x, auxs = jax.lax.scan(scan_body, x, params["blocks"], unroll=unroll)
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.layer_norm_eps)
    logits = jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(dt))
    logits = shard_logical(logits, ("batch", "seq", "vocab"), rules)
    if return_aux:
        return logits, jnp.mean(auxs)
    return logits


def apply_pipelined(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: Config,
    mesh,
    rules: Optional[LogicalRules] = None,
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    """Forward pass with the transformer blocks run as pipeline stages over
    the mesh's `pipeline` axis (GPipe schedule; parallel/pipeline.py).
    Embedding and the LM head run outside the pipeline on every stage."""
    from determined_tpu.parallel.pipeline import (
        pipeline_apply, pipeline_microbatches_default)

    b, s = tokens.shape
    # Activation dtype: cfg.dtype (bf16) on TPU — embedding, pipeline body,
    # and head all match the non-pipelined apply(). On the CPU backend
    # low-precision activation gradients around a partial-manual shard_map
    # crash XLA's SPMD partitioner ("Invalid binary instruction opcode
    # copy"), so everything runs f32 there (weights still cast in _block).
    compute = (cfg.dtype if jax.default_backend() in ("tpu", "axon")
               else jnp.float32)
    x = (_embed_tokens(params, tokens, cfg, rules, compute)
         + params["wpe"].astype(compute)[:s][None])
    x = shard_logical(x, ("batch", "seq", "embed"), rules)

    if cfg.num_experts > 1:
        raise NotImplementedError(
            "MoE blocks are not supported under pipeline parallelism yet — "
            "drop the pipeline axis or use a dense config"
        )

    def block(xx, lp):
        return _block(xx.astype(compute), lp, cfg, rules)[0].astype(compute)

    if cfg.remat:
        block = _remat(block, cfg)
    m = num_microbatches or pipeline_microbatches_default(mesh, b, rules)
    x = pipeline_apply(block, params["blocks"], x, mesh=mesh,
                       num_microbatches=m, rules=rules,
                       compute_dtype=compute)
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"],
                    cfg.layer_norm_eps)
    logits = jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(compute))
    return shard_logical(logits, ("batch", "seq", "vocab"), rules)


def loss_fn_pipelined(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: Config,
    mesh,
    rules: Optional[LogicalRules] = None,
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    tokens = batch["tokens"]
    if "targets" in batch:
        inputs, targets = tokens, batch["targets"]
    else:
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = apply_pipelined(params, inputs, cfg, mesh, rules,
                             num_microbatches)
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt.astype(jnp.float32))


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],  # {"tokens": [B, S+1]} or {"tokens","targets"}
    cfg: Config,
    rules: Optional[LogicalRules] = None,
) -> jax.Array:
    tokens = batch["tokens"]
    if "targets" in batch:
        inputs, targets = tokens, batch["targets"]
    else:
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = apply(params, inputs, cfg, rules, return_aux=True)
    # NLL without materialising a full fp32 log-softmax over the vocab:
    # nll = logsumexp(logits) - logits[target]. XLA fuses the f32 upcast into
    # the reduction, so the [B,S,V] array stays bf16 in HBM.
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = jnp.mean(lse - tgt.astype(jnp.float32))
    if cfg.num_experts > 1:
        nll = nll + cfg.moe_aux_coef * aux
    return nll
