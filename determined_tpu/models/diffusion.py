"""Denoising diffusion (DDPM) UNet — the diffusion-finetune workload
(BASELINE configs[4]; reference examples/diffusion/ finetunes Stable
Diffusion with HF diffusers + torch).

TPU-first design, not a port: plain-JAX NHWC UNet whose hot ops are conv
(MXU) and low-resolution self-attention (MXU matmuls), bf16 activations
with fp32 loss/norms, static shapes throughout (timesteps are data, not
Python control flow), `jax.checkpoint`-able blocks. The training objective
is epsilon-prediction with a cosine alpha-bar schedule (Nichol & Dhariwal,
arXiv:2102.09672); sampling is standard ancestral DDPM, jitted as one
`lax.scan` over timesteps so the whole reverse process is a single XLA
program.

Module idiom matches the other models (init / param_logical_axes / apply /
loss_fn) so the trial, Trainer, and GSPMD sharding path work unchanged.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from determined_tpu.parallel.sharding import LogicalRules, shard_logical


@dataclasses.dataclass(frozen=True)
class Config:
    image_size: int = 32
    channels: int = 3
    base_width: int = 64          # channel width at full resolution
    width_mults: Tuple[int, ...] = (1, 2, 4)  # per resolution level
    time_dim: int = 256
    groups: int = 8               # GroupNorm groups
    timesteps: int = 1000
    attn_at_lowest: bool = True
    dtype: Any = jnp.bfloat16     # activation dtype (params stay fp32)
    remat: bool = False

    @staticmethod
    def tiny() -> "Config":
        """CI/e2e size: 16px, thin widths, short schedule."""
        return Config(image_size=16, base_width=16, width_mults=(1, 2),
                      time_dim=32, groups=4, timesteps=64,
                      dtype=jnp.float32)


# ---------------------------------------------------------------------------
# schedule
# ---------------------------------------------------------------------------


def alpha_bars(cfg: Config) -> jax.Array:
    """Cosine cumulative noise schedule, fp32 [T]."""
    t = jnp.arange(cfg.timesteps + 1, dtype=jnp.float32) / cfg.timesteps
    f = jnp.cos((t + 0.008) / 1.008 * jnp.pi / 2) ** 2
    ab = f / f[0]
    return jnp.clip(ab[1:], 1e-5, 1.0)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def _conv_p(rng, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    w = jax.random.normal(rng, (kh, kw, cin, cout), jnp.float32)
    return {"kernel": w * math.sqrt(2.0 / fan_in),
            "bias": jnp.zeros((cout,), jnp.float32)}


def _dense_p(rng, din, dout, scale=None):
    w = jax.random.normal(rng, (din, dout), jnp.float32)
    return {"kernel": w * math.sqrt((2.0 if scale is None else scale) / din),
            "bias": jnp.zeros((dout,), jnp.float32)}


def _norm_p(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _resblock_p(rng, cin, cout, tdim):
    k = jax.random.split(rng, 4)
    p = {
        "norm1": _norm_p(cin),
        "conv1": _conv_p(k[0], 3, 3, cin, cout),
        "temb": _dense_p(k[1], tdim, cout),
        "norm2": _norm_p(cout),
        "conv2": _conv_p(k[2], 3, 3, cout, cout),
    }
    if cin != cout:
        p["skip"] = _conv_p(k[3], 1, 1, cin, cout)
    return p


def _attn_p(rng, c):
    k = jax.random.split(rng, 2)
    return {
        "norm": _norm_p(c),
        "qkv": _dense_p(k[0], c, 3 * c, scale=1.0),
        "out": _dense_p(k[1], c, c, scale=1.0),
    }


def init(rng: jax.Array, cfg: Config = Config()) -> Dict[str, Any]:
    widths = [cfg.base_width * m for m in cfg.width_mults]
    n_levels = len(widths)
    keys = iter(jax.random.split(rng, 64))
    p: Dict[str, Any] = {
        "time_mlp": {
            "fc1": _dense_p(next(keys), cfg.time_dim, cfg.time_dim),
            "fc2": _dense_p(next(keys), cfg.time_dim, cfg.time_dim),
        },
        "conv_in": _conv_p(next(keys), 3, 3, cfg.channels, widths[0]),
    }
    # down path: per level one resblock (+ downsample conv except last)
    down = []
    cin = widths[0]
    for i, w in enumerate(widths):
        lvl = {"res": _resblock_p(next(keys), cin, w, cfg.time_dim)}
        if i < n_levels - 1:
            lvl["down"] = _conv_p(next(keys), 3, 3, w, w)
        down.append(lvl)
        cin = w
    p["down"] = down
    mid = {"res1": _resblock_p(next(keys), cin, cin, cfg.time_dim),
           "res2": _resblock_p(next(keys), cin, cin, cfg.time_dim)}
    if cfg.attn_at_lowest:
        mid["attn"] = _attn_p(next(keys), cin)
    p["mid"] = mid
    up = []
    for i in reversed(range(n_levels)):
        w = widths[i]
        lvl = {"res": _resblock_p(next(keys), cin + w, w, cfg.time_dim)}
        if i > 0:
            lvl["up"] = _conv_p(next(keys), 3, 3, w, w)
        up.append(lvl)
        cin = w
    p["up"] = up
    p["norm_out"] = _norm_p(widths[0])
    out = _conv_p(next(keys), 3, 3, widths[0], cfg.channels)
    # zero-init the output conv: the denoiser starts as identity-ish,
    # standard DDPM practice for stable early training.
    out["kernel"] = jnp.zeros_like(out["kernel"])
    p["conv_out"] = out
    return p


def _conv_axes():
    return {"kernel": (None, None, "embed", "mlp"), "bias": ("mlp",)}


def _res_axes(has_skip: bool):
    a = {
        "norm1": {"scale": (None,), "bias": (None,)},
        "conv1": _conv_axes(),
        "temb": {"kernel": (None, "mlp"), "bias": ("mlp",)},
        "norm2": {"scale": (None,), "bias": (None,)},
        "conv2": _conv_axes(),
    }
    if has_skip:
        a["skip"] = _conv_axes()
    return a


def param_logical_axes(cfg: Config = Config()) -> Dict[str, Any]:
    """Conv kernels shard in/out channels over (embed, mlp) — with the
    standard fsdp rules that fsdp-shards every big kernel; norms and the
    tiny time MLP stay replicated."""
    widths = [cfg.base_width * m for m in cfg.width_mults]
    n = len(widths)
    down = []
    cin = widths[0]
    for i, w in enumerate(widths):
        lvl = {"res": _res_axes(cin != w)}
        if i < n - 1:
            lvl["down"] = _conv_axes()
        down.append(lvl)
        cin = w
    mid = {"res1": _res_axes(False), "res2": _res_axes(False)}
    if cfg.attn_at_lowest:
        mid["attn"] = {
            "norm": {"scale": (None,), "bias": (None,)},
            "qkv": {"kernel": ("embed", "heads"), "bias": ("heads",)},
            "out": {"kernel": ("heads", "embed"), "bias": ("embed",)},
        }
    up = []
    for i in reversed(range(n)):
        w = widths[i]
        lvl = {"res": _res_axes(True)}  # concat input always != w
        if i > 0:
            lvl["up"] = _conv_axes()
        up.append(lvl)
    return {
        "time_mlp": {"fc1": {"kernel": (None, None), "bias": (None,)},
                     "fc2": {"kernel": (None, None), "bias": (None,)}},
        # Boundary convs touch the image's 3 channels — unshardable dim;
        # replicate the in/out-channel axes there (they are tiny anyway).
        "conv_in": {"kernel": (None, None, None, "mlp"), "bias": ("mlp",)},
        "down": down,
        "mid": mid,
        "up": up,
        "norm_out": {"scale": (None,), "bias": (None,)},
        "conv_out": {"kernel": (None, None, "embed", None), "bias": (None,)},
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _timestep_embedding(t: jax.Array, dim: int) -> jax.Array:
    """Sinusoidal [B, dim] fp32 embedding of integer timesteps."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


def _group_norm(x, p, groups: int):
    # fp32 statistics regardless of activation dtype
    b, h, w, c = x.shape
    xf = x.astype(jnp.float32).reshape(b, h, w, groups, c // groups)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
    xf = xf.reshape(b, h, w, c)
    return (xf * p["scale"] + p["bias"]).astype(x.dtype)


def _conv(x, p, stride=1):
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y + p["bias"].astype(x.dtype)


def _resblock(x, temb, p, cfg: Config):
    h = _group_norm(x, p["norm1"], cfg.groups)
    h = _conv(jax.nn.silu(h), p["conv1"])
    t = jax.nn.silu(temb) @ p["temb"]["kernel"].astype(temb.dtype) + \
        p["temb"]["bias"].astype(temb.dtype)
    h = h + t[:, None, None, :].astype(h.dtype)
    h = _group_norm(h, p["norm2"], cfg.groups)
    h = _conv(jax.nn.silu(h), p["conv2"])
    skip = _conv(x, p["skip"]) if "skip" in p else x
    return h + skip


def _self_attention(x, p, cfg: Config):
    b, hh, ww, c = x.shape
    h = _group_norm(x, p["norm"], cfg.groups)
    flat = h.reshape(b, hh * ww, c)
    qkv = flat @ p["qkv"]["kernel"].astype(flat.dtype) + \
        p["qkv"]["bias"].astype(flat.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    logits = jnp.einsum("bqc,bkc->bqk", q, k).astype(jnp.float32)
    probs = jax.nn.softmax(logits / math.sqrt(c), axis=-1).astype(v.dtype)
    o = jnp.einsum("bqk,bkc->bqc", probs, v)
    o = o @ p["out"]["kernel"].astype(o.dtype) + \
        p["out"]["bias"].astype(o.dtype)
    return x + o.reshape(b, hh, ww, c)


def _upsample(x):
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, 2 * h, 2 * w, c)


def apply(params: Dict[str, Any], x: jax.Array, t: jax.Array,
          cfg: Config = Config(),
          rules: Optional[LogicalRules] = None) -> jax.Array:
    """Predict the noise in x_t. x: [B, H, W, C] in [-1, 1]; t: [B] int32.
    Returns eps_hat with x's shape (cfg.dtype activations, fp32 out)."""
    x = x.astype(cfg.dtype)
    temb = _timestep_embedding(t, cfg.time_dim)
    tm = params["time_mlp"]
    temb = jax.nn.silu(temb @ tm["fc1"]["kernel"] + tm["fc1"]["bias"])
    temb = temb @ tm["fc2"]["kernel"] + tm["fc2"]["bias"]

    block = _resblock
    if cfg.remat:
        block = jax.checkpoint(_resblock, static_argnums=(3,))

    def constrain(h):
        # Activation constraint at block boundaries: keep the batch dim on
        # (data, fsdp) so GSPMD doesn't drift layouts between levels. The
        # channel dim is left to propagation — its size varies (concats).
        return shard_logical(h, ("batch", None, None, None), rules)

    h = constrain(_conv(x, params["conv_in"]))
    skips = []
    n = len(params["down"])
    for i, lvl in enumerate(params["down"]):
        h = constrain(block(h, temb, lvl["res"], cfg))
        skips.append(h)
        if i < n - 1:
            h = _conv(h, lvl["down"], stride=2)
    h = block(h, temb, params["mid"]["res1"], cfg)
    if "attn" in params["mid"]:
        h = _self_attention(h, params["mid"]["attn"], cfg)
    h = block(h, temb, params["mid"]["res2"], cfg)
    for j, lvl in enumerate(params["up"]):
        i = n - 1 - j
        h = jnp.concatenate([h, skips[i]], axis=-1)
        h = constrain(block(h, temb, lvl["res"], cfg))
        if i > 0:
            h = _upsample(h)
            h = _conv(h, lvl["up"])
    h = _group_norm(h, params["norm_out"], cfg.groups)
    h = _conv(jax.nn.silu(h), params["conv_out"])
    return h.astype(jnp.float32)


# ---------------------------------------------------------------------------
# training objective + sampling
# ---------------------------------------------------------------------------


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            cfg: Config = Config(), rng: Optional[jax.Array] = None,
            rules: Optional[LogicalRules] = None):
    """Epsilon-prediction MSE at uniformly sampled timesteps.
    batch["images"]: [B, H, W, C] in [-1, 1]."""
    x0 = batch["images"].astype(jnp.float32)
    b = x0.shape[0]
    if rng is None:
        rng = jax.random.PRNGKey(0)
    kt, ke = jax.random.split(rng)
    t = jax.random.randint(kt, (b,), 0, cfg.timesteps)
    eps = jax.random.normal(ke, x0.shape, jnp.float32)
    ab = alpha_bars(cfg)[t][:, None, None, None]
    xt = jnp.sqrt(ab) * x0 + jnp.sqrt(1.0 - ab) * eps
    eps_hat = apply(params, xt, t, cfg, rules)
    loss = jnp.mean((eps_hat - eps) ** 2)
    return loss, {"loss": loss}


def sample(params: Dict[str, Any], rng: jax.Array, n: int,
           cfg: Config = Config()) -> jax.Array:
    """Ancestral DDPM sampling as ONE lax.scan over timesteps (a single
    XLA program; no per-step host round-trips). Returns [n, H, W, C]."""
    ab = alpha_bars(cfg)
    ab_prev = jnp.concatenate([jnp.ones((1,)), ab[:-1]])
    alphas = ab / ab_prev
    betas = 1.0 - alphas
    shape = (n, cfg.image_size, cfg.image_size, cfg.channels)
    k0, kloop = jax.random.split(rng)
    x_t = jax.random.normal(k0, shape, jnp.float32)

    def step(carry, i):
        x, key = carry
        t = cfg.timesteps - 1 - i
        key, knoise = jax.random.split(key)
        tb = jnp.full((n,), t, jnp.int32)
        eps_hat = apply(params, x, tb, cfg)
        coef = betas[t] / jnp.sqrt(1.0 - ab[t])
        mean = (x - coef * eps_hat) / jnp.sqrt(alphas[t])
        noise = jax.random.normal(knoise, shape, jnp.float32)
        x = mean + jnp.where(t > 0, jnp.sqrt(betas[t]), 0.0) * noise
        return (x, key), None

    (x_t, _), _ = jax.lax.scan(step, (x_t, kloop),
                               jnp.arange(cfg.timesteps))
    return jnp.clip(x_t, -1.0, 1.0)
