"""MNIST CNN — the "minimum slice" workload (SURVEY.md §7 step 2; reference
examples/tutorials/mnist_pytorch). Plain-JAX conv net, single-chip friendly."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from determined_tpu.parallel.sharding import LogicalRules


@dataclasses.dataclass(frozen=True)
class Config:
    n_classes: int = 10
    c1: int = 32
    c2: int = 64
    hidden: int = 128
    dtype: Any = jnp.float32


def init(rng: jax.Array, cfg: Config = Config()) -> Dict[str, Any]:
    k = jax.random.split(rng, 4)
    he = jax.nn.initializers.he_normal()
    return {
        "conv1": {"kernel": he(k[0], (3, 3, 1, cfg.c1), cfg.dtype), "bias": jnp.zeros((cfg.c1,), cfg.dtype)},
        "conv2": {"kernel": he(k[1], (3, 3, cfg.c1, cfg.c2), cfg.dtype), "bias": jnp.zeros((cfg.c2,), cfg.dtype)},
        "fc1": {"kernel": he(k[2], (7 * 7 * cfg.c2, cfg.hidden), cfg.dtype), "bias": jnp.zeros((cfg.hidden,), cfg.dtype)},
        "fc2": {"kernel": he(k[3], (cfg.hidden, cfg.n_classes), cfg.dtype), "bias": jnp.zeros((cfg.n_classes,), cfg.dtype)},
    }


def param_logical_axes(cfg: Config = Config()) -> Dict[str, Any]:
    return {
        "conv1": {"kernel": (None, None, None, None), "bias": (None,)},
        "conv2": {"kernel": (None, None, None, None), "bias": (None,)},
        "fc1": {"kernel": ("embed", "mlp"), "bias": ("mlp",)},
        "fc2": {"kernel": ("mlp", None), "bias": (None,)},
    }


def _conv(x, p, stride=1):
    y = jax.lax.conv_general_dilated(
        x, p["kernel"], (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + p["bias"]


def apply(params: Dict[str, Any], images: jax.Array, cfg: Config = Config(),
          rules: Optional[LogicalRules] = None) -> jax.Array:
    """images: [B, 28, 28, 1] → logits [B, 10]."""
    x = _conv(images.astype(cfg.dtype), params["conv1"])
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    x = jax.nn.relu(_conv(x, params["conv2"]))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["kernel"] + params["fc1"]["bias"])
    return x @ params["fc2"]["kernel"] + params["fc2"]["bias"]


def loss_fn(params, batch: Dict[str, jax.Array], cfg: Config = Config(),
            rules: Optional[LogicalRules] = None):
    logits = apply(params, batch["images"], cfg, rules)
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    acc = jnp.mean(jnp.argmax(logits, -1) == labels)
    return jnp.mean(nll), {"accuracy": acc}
