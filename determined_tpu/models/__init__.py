"""Reference model families (the workloads in BASELINE.md).

Models are plain-JAX: params are nested dicts of jnp arrays, each model module
exposes ``Config``, ``init(rng, cfg)``, ``apply(params, batch, cfg)``,
``param_logical_axes(cfg)`` (pytree of logical-axis tuples for GSPMD layout,
see determined_tpu.parallel.sharding) and ``loss_fn``.
"""
