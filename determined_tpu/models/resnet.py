"""ResNet (CIFAR/ImageNet variants) in plain JAX, TPU-first.

Covers the reference's CIFAR-10/ImageNet workloads (BASELINE.md: CIFAR-10
ResNet on v5e-8; samples/sec/chip on ResNet-50). NHWC layout + bf16 compute
(convs hit the MXU as implicit GEMMs); BatchNorm carries running stats in a
separate `batch_stats` collection; cross-replica BN stats are synchronised
with `psum` only when an axis name is present (shard_map/pmap contexts) —
under plain GSPMD data parallel, per-shard stats are the standard choice.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Config:
    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # resnet18
    num_filters: int = 64
    n_classes: int = 10
    bottleneck: bool = False
    cifar_stem: bool = True  # 3x3 stem, no maxpool (CIFAR); else 7x7/2 + pool
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @staticmethod
    def resnet18_cifar(n_classes: int = 10) -> "Config":
        return Config()

    @staticmethod
    def resnet50(n_classes: int = 1000) -> "Config":
        return Config(
            stage_sizes=(3, 4, 6, 3), bottleneck=True, cifar_stem=False,
            n_classes=n_classes,
        )


def _conv_init(rng, shape, dtype):
    return jax.nn.initializers.he_normal()(rng, shape, dtype)


def _bn_init(c, dtype):
    return {
        "scale": jnp.ones((c,), dtype),
        "bias": jnp.zeros((c,), dtype),
    }


def _block_channels(cfg: Config, stage: int) -> Tuple[int, int]:
    width = cfg.num_filters * (2 ** stage)
    out = width * (4 if cfg.bottleneck else 1)
    return width, out


def init(rng: jax.Array, cfg: Config = Config()) -> Dict[str, Any]:
    pd = cfg.param_dtype
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    n_keys = 4 + sum(cfg.stage_sizes) * 4
    keys = iter(jax.random.split(rng, n_keys))

    stem_k = 3 if cfg.cifar_stem else 7
    params["stem"] = {"kernel": _conv_init(next(keys), (stem_k, stem_k, 3, cfg.num_filters), pd)}
    params["stem_bn"] = _bn_init(cfg.num_filters, pd)
    stats["stem_bn"] = {"mean": jnp.zeros((cfg.num_filters,), pd), "var": jnp.ones((cfg.num_filters,), pd)}

    in_c = cfg.num_filters
    for s, n_blocks in enumerate(cfg.stage_sizes):
        width, out_c = _block_channels(cfg, s)
        for b in range(n_blocks):
            name = f"stage{s}_block{b}"
            stride = 2 if (b == 0 and s > 0) else 1
            bp: Dict[str, Any] = {}
            bs: Dict[str, Any] = {}
            if cfg.bottleneck:
                shapes = [(1, 1, in_c, width), (3, 3, width, width), (1, 1, width, out_c)]
            else:
                shapes = [(3, 3, in_c, width), (3, 3, width, out_c)]
            for i, shp in enumerate(shapes):
                bp[f"conv{i}"] = {"kernel": _conv_init(next(keys), shp, pd)}
                bp[f"bn{i}"] = _bn_init(shp[-1], pd)
                bs[f"bn{i}"] = {"mean": jnp.zeros((shp[-1],), pd), "var": jnp.ones((shp[-1],), pd)}
            if stride != 1 or in_c != out_c:
                bp["proj"] = {"kernel": _conv_init(next(keys), (1, 1, in_c, out_c), pd)}
                bp["proj_bn"] = _bn_init(out_c, pd)
                bs["proj_bn"] = {"mean": jnp.zeros((out_c,), pd), "var": jnp.ones((out_c,), pd)}
            params[name] = bp
            stats[name] = bs
            in_c = out_c

    params["head"] = {
        "kernel": jax.nn.initializers.zeros(next(keys), (in_c, cfg.n_classes), pd),
        "bias": jnp.zeros((cfg.n_classes,), pd),
    }
    return params, stats


def param_logical_axes(cfg: Config = Config()) -> Any:
    """Convs replicated (small relative to activations); head over mlp."""
    params, _ = jax.eval_shape(lambda r: init(r, cfg), jax.random.PRNGKey(0))
    # Structural: every leaf replicated except the head kernel.
    axes = jax.tree_util.tree_map(lambda x: tuple(None for _ in x.shape), params)
    axes["head"]["kernel"] = ("embed", "mlp")
    return axes


def _bn(x, p, st, cfg: Config, train: bool, new_stats: Optional[dict] = None, name: str = ""):
    # Never materialize an fp32 copy of the activation: statistics are
    # f32-ACCUMULATED reductions over the bf16 tensor (XLA fuses the square
    # into the reduce), and normalization collapses to one bf16 per-channel
    # affine `x*a + b` that XLA fuses into the conv epilogue. The naive
    # x.astype(f32) formulation tripled HBM traffic per BN (read bf16,
    # write f32, re-read f32 ×2 passes) AND saved fp32 residuals for the
    # backward — it alone capped ResNet-50 at ~14% MFU on v5e.
    if train:
        mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
        mean2 = jnp.mean(jax.lax.square(x.astype(jnp.float32)), axis=(0, 1, 2))
        var = jnp.maximum(mean2 - jax.lax.square(mean), 0.0)
        if new_stats is not None:
            m = cfg.bn_momentum
            new_stats[name] = {
                "mean": m * st["mean"] + (1 - m) * mean,
                "var": m * st["var"] + (1 - m) * var,
            }
    else:
        mean, var = st["mean"], st["var"]
    inv = jax.lax.rsqrt(var + cfg.bn_eps)
    a = p["scale"].astype(jnp.float32) * inv
    b = p["bias"].astype(jnp.float32) - mean * a
    return x * a.astype(x.dtype) + b.astype(x.dtype)


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x, kernel.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def apply(
    params: Dict[str, Any],
    stats: Dict[str, Any],
    images: jax.Array,  # [B, H, W, 3]
    cfg: Config = Config(),
    train: bool = False,
) -> Tuple[jax.Array, Dict[str, Any]]:
    """→ (logits [B, n_classes] fp32, updated batch_stats)."""
    new_stats: Dict[str, Any] = {}

    x = images.astype(cfg.dtype)
    stride = 1 if cfg.cifar_stem else 2
    x = _conv(x, params["stem"]["kernel"], stride)
    ns: dict = {}
    x = _bn(x, params["stem_bn"], stats["stem_bn"], cfg, train, ns, "bn")
    new_stats["stem_bn"] = ns.get("bn", stats["stem_bn"])
    x = jax.nn.relu(x)
    if not cfg.cifar_stem:
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")

    in_c = cfg.num_filters
    for s, n_blocks in enumerate(cfg.stage_sizes):
        width, out_c = _block_channels(cfg, s)
        for b in range(n_blocks):
            name = f"stage{s}_block{b}"
            bp, bst = params[name], stats[name]
            stride = 2 if (b == 0 and s > 0) else 1
            residual = x
            bns: dict = {}
            if cfg.bottleneck:
                y = jax.nn.relu(_bn(_conv(x, bp["conv0"]["kernel"], 1), bp["bn0"], bst["bn0"], cfg, train, bns, "bn0"))
                y = jax.nn.relu(_bn(_conv(y, bp["conv1"]["kernel"], stride), bp["bn1"], bst["bn1"], cfg, train, bns, "bn1"))
                y = _bn(_conv(y, bp["conv2"]["kernel"], 1), bp["bn2"], bst["bn2"], cfg, train, bns, "bn2")
            else:
                y = jax.nn.relu(_bn(_conv(x, bp["conv0"]["kernel"], stride), bp["bn0"], bst["bn0"], cfg, train, bns, "bn0"))
                y = _bn(_conv(y, bp["conv1"]["kernel"], 1), bp["bn1"], bst["bn1"], cfg, train, bns, "bn1")
            if "proj" in bp:
                residual = _bn(_conv(x, bp["proj"]["kernel"], stride), bp["proj_bn"], bst["proj_bn"], cfg, train, bns, "proj_bn")
            x = jax.nn.relu(y + residual)
            new_stats[name] = {k: bns.get(k, bst[k]) for k in bst}
            in_c = out_c

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head"]["kernel"].astype(jnp.float32) + params["head"]["bias"].astype(jnp.float32)
    return logits, new_stats


def loss_fn(params, stats, batch: Dict[str, jax.Array], rng=None,
            cfg: Config = Config(), train: bool = True):
    """Stateful-protocol loss (see train.step.make_train_step(stateful=True)):
    → (loss, metrics, new_batch_stats)."""
    logits, new_stats = apply(params, stats, batch["images"], cfg, train=train)
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits)
    nll = jnp.mean(-jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0])
    acc = jnp.mean(jnp.argmax(logits, -1) == labels)
    return nll, {"accuracy": acc}, new_stats
