"""HTTP front-end for a serve replica.

Small and dependency-free (http.server, like the exec task servers): one
POST endpoint that blocks until the batcher completes the request, plus
stats/health for load balancers and the master proxy.

  POST /v1/generate   {"tokens": [...], "max_new_tokens": 16,
                       "temperature": 0.0, "eos_id": null,
                       "timeout_s": 120}
      200 {"id", "tokens", "prompt_tokens", "latency_ms", "queue_ms"}
      400 bad request (prompt too long for every bucket, bad body)
      429 admission queue full            (Retry-After: 1)
      503 draining — not admitting        (Retry-After: 5)
      504 request accepted but not finished within timeout_s

  GET /v1/stats       batcher + engine counters (occupancy, KV blocks,
                      queue depth, compile times)
  GET /metrics        the same counters in Prometheus text exposition
                      (docs/observability.md) — a fleet scrape of every
                      node sees serving replicas next to master/agent,
                      and queue depth + occupancy are the autoscaling
                      signal
  GET /healthz        {"status": "ok"|"draining"}

The thread-per-request server is intentional: generate handlers spend
their life blocked on a result event, so threads are cheap, and the
batcher thread is the only device consumer regardless of fan-in.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from determined_tpu.serve.scheduler import (
    ContinuousBatcher,
    Draining,
    QueueFull,
    Request,
)

logger = logging.getLogger("determined_tpu.serve")

DEFAULT_REQUEST_TIMEOUT_S = 120.0


def _hist_exposition(name: str, wire: Dict[str, Any]) -> list:
    """One histogram in Prometheus text format from the LatencyHist wire
    form (cumulative counts + le boundaries)."""
    lines = [f"# TYPE {name} histogram"]
    les = wire.get("le") or []
    counts = wire.get("counts") or []
    for le, c in zip(les, counts):
        lines.append(f'{name}_bucket{{le="{le}"}} {c}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {wire.get("count", 0)}')
    lines.append(f"{name}_sum {wire.get('sum', 0.0)}")
    lines.append(f"{name}_count {wire.get('count', 0)}")
    return lines


def prometheus_exposition(stats: Dict[str, Any],
                          latency_wire: Optional[Dict[str, Any]] = None
                          ) -> str:
    """Fold ContinuousBatcher.stats() into Prometheus text format (names
    registered in common/metric_names.py SERVE_METRICS). `latency_wire`
    is the heartbeat-form histogram dict ({ttft,tpot,e2e,queue_wait} →
    le/counts/sum/count) — the TTFT/TPOT/e2e/queue-wait SLO histograms of
    docs/serving.md "Request latency & SLOs"."""
    kv = stats.get("kv_blocks", {}) or {}
    lines = [
        "# TYPE det_serve_queue_depth gauge",
        f"det_serve_queue_depth {stats.get('queue_depth', 0)}",
        "# TYPE det_serve_active_requests gauge",
        f"det_serve_active_requests {stats.get('active', 0)}",
        "# TYPE det_serve_draining gauge",
        f"det_serve_draining {1 if stats.get('draining') else 0}",
        "# TYPE det_serve_kv_blocks_free gauge",
        f"det_serve_kv_blocks_free {kv.get('free_blocks', 0)}",
        "# TYPE det_serve_kv_blocks_used gauge",
        f"det_serve_kv_blocks_used {kv.get('used_blocks', 0)}",
        "# TYPE det_serve_kv_blocks_total gauge",
        f"det_serve_kv_blocks_total {kv.get('num_blocks', 0)}",
        "# TYPE det_serve_prefix_cache_hit_rate gauge",
        "det_serve_prefix_cache_hit_rate "
        f"{kv.get('prefix_cache_hit_rate', 0.0)}",
        "# TYPE det_serve_requests_total counter",
        f"det_serve_requests_total {stats.get('completed', 0)}",
        "# TYPE det_serve_tokens_total counter",
        f"det_serve_tokens_total {stats.get('generated_tokens', 0)}",
    ]
    if latency_wire:
        for name, key in (
            ("det_serve_ttft_seconds", "ttft"),
            ("det_serve_tpot_seconds", "tpot"),
            ("det_serve_e2e_seconds", "e2e"),
            ("det_serve_queue_wait_seconds", "queue_wait"),
        ):
            lines.extend(_hist_exposition(name, latency_wire.get(key) or {}))
    return "\n".join(lines) + "\n"


def _make_handler(batcher: ContinuousBatcher):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet: task log carries ours
            logger.debug("http: " + fmt, *args)

        def _send(self, status: int, body: Dict[str, Any],
                  headers: Optional[Dict[str, str]] = None) -> None:
            data = json.dumps(body).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path == "/healthz":
                draining = batcher.queue.draining
                self._send(200, {"status": "draining" if draining
                                 else "ok"})
                return
            if self.path == "/v1/stats":
                stats = batcher.stats()
                stats["engine"] = batcher.engine.stats()
                stats["retry_after_hint_s"] = batcher.retry_after_hint()
                self._send(200, stats)
                return
            if self.path == "/metrics":
                latency = batcher.heartbeat_stats().get("latency")
                data = prometheus_exposition(
                    batcher.stats(), latency_wire=latency).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._send(404, {"error": "not found"})

        def do_POST(self):  # noqa: N802
            if self.path != "/v1/generate":
                self._send(404, {"error": "not found"})
                return
            # X-Request-Id names the request's trace: the master router
            # mints one per routed request (accepting a caller-supplied
            # id) and the replica's span tree rides it, so
            # `det serve trace <deployment> <request-id>` finds the whole
            # router→replica tree under one id.
            rid = (self.headers.get("X-Request-Id") or "").strip() or None
            # Adapter routing (docs/serving.md "Model lifecycle"): the
            # `model` body field (or X-Model header) names a resident
            # fine-tune; unknown names 400 below via submit()'s
            # validation — never a silent base-model answer.
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                model = (str(body.get("model")
                             or self.headers.get("X-Model") or "").strip()
                         or None)
                req = Request(
                    tokens=body["tokens"],
                    max_new_tokens=int(body.get("max_new_tokens", 16)),
                    temperature=float(body.get("temperature", 0.0)),
                    eos_id=body.get("eos_id"),
                    request_id=rid,
                    model=model,
                )
                timeout = float(
                    body.get("timeout_s", DEFAULT_REQUEST_TIMEOUT_S))
            except (KeyError, TypeError, ValueError) as e:
                self._send(400, {"error": f"bad request: {e}"})
                return
            try:
                batcher.submit(req)
            except Draining as e:
                self._send(503, {"error": str(e)}, {"Retry-After": "5"})
                return
            except QueueFull as e:
                # Computed backoff: queue depth × smoothed service time
                # over the batch slots — a hint the harness Session (and
                # the master router, which propagates the header) can act
                # on instead of a bare 429.
                hint = str(batcher.retry_after_hint())
                self._send(429, {"error": str(e)}, {"Retry-After": hint})
                return
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            rid_hdr = {"X-Request-Id": req.id}
            try:
                self._send(200, req.result(timeout), rid_hdr)
            except TimeoutError:
                self._send(504, {"error": "request timed out",
                                 "id": req.id}, rid_hdr)
            except RuntimeError as e:
                self._send(500, {"error": str(e), "id": req.id}, rid_hdr)

    return Handler


class ServingServer:
    """ThreadingHTTPServer wrapper with deterministic lifecycle."""

    def __init__(self, batcher: ContinuousBatcher, host: str = "0.0.0.0",
                 port: int = 0):
        self.batcher = batcher
        self._httpd = ThreadingHTTPServer(
            (host, port), _make_handler(batcher))
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "ServingServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serve-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
