"""KV-cached GPT-2 inference steps (prefill + single-token decode).

The training forward pass (models/gpt2.apply) recomputes attention over the
whole context every call — O(S²) per generated token. Serving splits it the
standard way:

  - **prefill**: one full causal pass over the (bucket-padded) prompt,
    writing every position's K/V into the sequence's cache lane and
    returning the next-token logits. Compiled per prompt bucket, so a small
    set of AOT executables covers every prompt length.
  - **decode**: one token per active slot per call — each slot attends over
    its cached K/V only. One compiled executable regardless of batch
    composition; the continuous batcher joins/retires sequences purely by
    editing host-side slot state.

Two cache layouts share this module (both stacked over layers exactly
like the training params, so every path `lax.scan`s the same block
structure):

  - **paged** (the default; `init_paged_cache`/`paged_prefill`/
    `paged_decode_step`): a block pool `[L, num_blocks + 1, block_size,
    H, Dh]` addressed through per-sequence block tables — admission
    bounds real HBM and prompt prefixes can be shared (docs/serving.md
    "Paged KV & prefix caching");
  - **slot-dense** (legacy, kept for A/B): `[L, slots, max_seq, H, Dh]`,
    one private lane per slot.

Positions beyond a sequence's current length hold stale bytes; the
decode mask (`index <= position`) never admits a stale index before the
step that overwrites it, and paged padded/inactive writes land in a
dedicated trash block.

Works for dense and MoE blocks (the MoE FFN routes per token, so a
1-token decode step reuses ops/moe.moe_block unchanged). All functions are
shape-static and jit/AOT-friendly; tier-1 exercises them on the CPU
backend via the `_jax_compat` shims.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from determined_tpu.models.gpt2 import Config, _embed_tokens, _layer_norm
from determined_tpu.parallel.sharding import LogicalRules, shard_logical


def init_cache(
    cfg: Config, slots: int, max_seq: int, dtype: Any = None
) -> Dict[str, jax.Array]:
    """Zeroed KV cache: {"k","v"}: [L, slots, max_seq, H, Dh]."""
    if max_seq > cfg.n_positions:
        raise ValueError(
            f"max_seq {max_seq} exceeds the model's position table "
            f"({cfg.n_positions})")
    dt = dtype or cfg.dtype
    shape = (cfg.n_layer, slots, max_seq, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def cache_bytes(cfg: Config, slots: int, max_seq: int,
                dtype: Any = None) -> int:
    """HBM footprint of the cache (both K and V) — admission budgeting."""
    dt = jnp.dtype(dtype or cfg.dtype)
    per = cfg.n_layer * slots * max_seq * cfg.n_head * cfg.head_dim
    return 2 * per * dt.itemsize


def _qkv(x, lp, cfg: Config):
    """x: [B, S, D] → q, k, v: [B, S, H, Dh]."""
    b, s, _ = x.shape
    dt = cfg.dtype
    qkv = jnp.einsum("bsd,de->bse", x, lp["qkv"]["kernel"].astype(dt)) + lp[
        "qkv"]["bias"].astype(dt)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = (b, s, cfg.n_head, cfg.head_dim)
    return q.reshape(shape), k.reshape(shape), v.reshape(shape)


def _mlp(y, lp, cfg: Config, rules: Optional[LogicalRules]):
    """The block's FFN — dense or token-routed MoE, matching _block."""
    dt = cfg.dtype
    if cfg.num_experts > 1:
        from determined_tpu.ops.moe import moe_block

        down, _ = moe_block(
            y, lp["moe"], cfg.num_experts, top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor, rules=rules,
        )
        return down
    up = jnp.einsum("bsd,df->bsf", y, lp["mlp_up"]["kernel"].astype(dt)) + lp[
        "mlp_up"]["bias"].astype(dt)
    up = shard_logical(up, ("batch", "seq", "mlp"), rules)
    up = jax.nn.gelu(up, approximate=True)
    return (
        jnp.einsum("bsf,fd->bsd", up, lp["mlp_down"]["kernel"].astype(dt))
        + lp["mlp_down"]["bias"].astype(dt)
    )


def _finish(params, x, cfg: Config, rules: Optional[LogicalRules]):
    """Final layernorm + LM head → logits [..., vocab]."""
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"],
                    cfg.layer_norm_eps)
    logits = jnp.einsum("bsd,vd->bsv", x, params["wte"].astype(cfg.dtype))
    return shard_logical(logits, ("batch", "seq", "vocab"), rules)


# ------------------------------------------------------------- adapters
#
# Multi-adapter serving (docs/serving.md "Model lifecycle"): many
# fine-tunes share ONE base executable and ONE KV pool. An adapter is the
# (tied) embedding/LM-head table of a head-tuned checkpoint; the stack
# `[A+1, vocab, d_model]` (index 0 = the base table) rides every compiled
# call like params do, and a per-slot adapter index selects each lane's
# table at the only two places the table is read — token embedding and the
# final logits projection. The transformer body (and therefore the cached
# K/V) stays the base's for every adapter, which is exactly what lets one
# executable and one block pool serve thousands of fine-tunes: selection
# is a gather + a batched matmul, never a recompile.


def _embed_adapter(adapters: jax.Array, idx: jax.Array,
                   tokens: jax.Array, dtype) -> jax.Array:
    """Per-lane token embedding from the adapter stack.

    adapters: [A+1, V, D]. idx scalar (prefill: one lane, tokens [S] →
    [S, D]) or [slots] (decode: one token per lane, tokens [slots] →
    [slots, D]). Same gather `wte[tokens]` as _embed_tokens, with wte
    selected per lane (serving runs unsharded — the one-hot Megatron
    path is a training concern)."""
    sel = jnp.take(adapters, idx, axis=0).astype(dtype)
    if idx.ndim == 0:
        return sel[tokens]  # [S, D]
    return jnp.take_along_axis(
        sel, tokens[:, None, None], axis=1)[:, 0, :]  # [slots, D]


def _finish_adapter(params, x, adapters: jax.Array, idx: jax.Array,
                    cfg: Config, rules: Optional[LogicalRules]):
    """_finish with the LM head selected per lane from the adapter stack.

    x: [B, S, D]; idx: [] (prefill, B==1) or [slots] (decode, S==1)."""
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"],
                    cfg.layer_norm_eps)
    sel = jnp.take(adapters, idx, axis=0).astype(cfg.dtype)
    if idx.ndim == 0:
        logits = jnp.einsum("bsd,vd->bsv", x, sel)
    else:
        logits = jnp.einsum("sqd,svd->sqv", x, sel)
    return shard_logical(logits, ("batch", "seq", "vocab"), rules)


# ---------------------------------------------------------------- prefill


def prefill(
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,   # [bucket] int32, right-padded to the bucket size
    length: jax.Array,   # scalar int32: real prompt length (<= bucket)
    slot: jax.Array,     # scalar int32: cache lane to fill
    cfg: Config,
    rules: Optional[LogicalRules] = None,
    adapters: Optional[jax.Array] = None,   # [A+1, V, D] stack
    slot_adapter: Optional[jax.Array] = None,  # scalar int32 stack index
) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """Run the prompt through the model, filling cache lane `slot`.

    Returns (cache', next_token_logits [vocab]). Padded positions compute
    garbage K/V but the decode mask never reads an index the decode loop
    has not since overwritten (module docstring).
    """
    s = tokens.shape[0]
    dt = cfg.dtype
    if adapters is None:
        x = _embed_tokens(params, tokens[None], cfg, rules, dt)
    else:
        x = _embed_adapter(adapters, slot_adapter, tokens, dt)[None]
    x = x + params["wpe"].astype(dt)[:s][None]
    x = shard_logical(x, ("batch", "seq", "embed"), rules)
    causal = jnp.tril(jnp.ones((s, s), jnp.bool_))
    valid = jnp.arange(s)[None, :] < length  # [1, S] key-side padding mask
    mask = causal & valid

    def body(carry, layer_in):
        xx = carry
        lp, k_lane, v_lane = layer_in
        y = _layer_norm(xx, lp["ln1"]["scale"], lp["ln1"]["bias"],
                        cfg.layer_norm_eps)
        q, k, v = _qkv(y, lp, cfg)
        # Write this layer's K/V for the whole prompt into the slot's lane.
        k_lane = jax.lax.dynamic_update_slice(
            k_lane, k.astype(k_lane.dtype), (slot, 0, 0, 0))
        v_lane = jax.lax.dynamic_update_slice(
            v_lane, v.astype(v_lane.dtype), (slot, 0, 0, 0))
        scale = 1.0 / math.sqrt(cfg.head_dim)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        logits = jnp.where(mask[None, None], logits * scale,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        attn = attn.reshape(xx.shape)
        attn = (jnp.einsum("bsd,de->bse", attn,
                           lp["attn_out"]["kernel"].astype(dt))
                + lp["attn_out"]["bias"].astype(dt))
        xx = xx + attn
        y = _layer_norm(xx, lp["ln2"]["scale"], lp["ln2"]["bias"],
                        cfg.layer_norm_eps)
        xx = xx + _mlp(y, lp, cfg, rules)
        xx = shard_logical(xx, ("batch", "seq", "embed"), rules)
        return xx, (k_lane, v_lane)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    if adapters is None:
        logits = _finish(params, x, cfg, rules)  # [1, S, V]
    else:
        logits = _finish_adapter(params, x, adapters, slot_adapter, cfg,
                                 rules)
    last = jax.lax.dynamic_index_in_dim(
        logits[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False)
    return {"k": new_k, "v": new_v}, last.astype(jnp.float32)


# ---------------------------------------------------------------- decode


def decode_step(
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,     # [slots] int32: last emitted token per slot
    positions: jax.Array,  # [slots] int32: index this step writes/attends at
    cfg: Config,
    rules: Optional[LogicalRules] = None,
    adapters: Optional[jax.Array] = None,      # [A+1, V, D] stack
    slot_adapters: Optional[jax.Array] = None,  # [slots] int32 stack index
) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """One decode step for every slot → (cache', logits [slots, vocab]).

    Inactive slots simply ride along (position 0, result discarded by the
    batcher) — the executable never depends on which lanes are live, so
    joining and retiring sequences costs zero recompiles.
    """
    slots = tokens.shape[0]
    max_seq = cache["k"].shape[2]
    dt = cfg.dtype
    if adapters is None:
        x = _embed_tokens(params, tokens[:, None], cfg, rules, dt)
    else:
        x = _embed_adapter(adapters, slot_adapters, tokens, dt)[:, None]
    pos_emb = jnp.take(params["wpe"].astype(dt), positions, axis=0)
    x = x + pos_emb[:, None]
    x = shard_logical(x, ("batch", "seq", "embed"), rules)
    lane = jnp.arange(slots)
    # index <= position admits the prompt, every prior decode step, and the
    # K/V this very step writes — never a stale lane byte.
    mask = jnp.arange(max_seq)[None] <= positions[:, None]  # [slots, max_seq]

    def body(carry, layer_in):
        xx = carry  # [slots, 1, D]
        lp, k_lane, v_lane = layer_in
        y = _layer_norm(xx, lp["ln1"]["scale"], lp["ln1"]["bias"],
                        cfg.layer_norm_eps)
        q, k, v = _qkv(y, lp, cfg)  # [slots, 1, H, Dh]
        k_lane = k_lane.at[lane, positions].set(
            k[:, 0].astype(k_lane.dtype))
        v_lane = v_lane.at[lane, positions].set(
            v[:, 0].astype(v_lane.dtype))
        scale = 1.0 / math.sqrt(cfg.head_dim)
        logits = jnp.einsum(
            "bhd,bmhd->bhm", q[:, 0], k_lane).astype(jnp.float32)
        logits = jnp.where(mask[:, None], logits * scale,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhm,bmhd->bhd", probs, v_lane)
        attn = attn.reshape(slots, 1, -1)
        attn = (jnp.einsum("bsd,de->bse", attn,
                           lp["attn_out"]["kernel"].astype(dt))
                + lp["attn_out"]["bias"].astype(dt))
        xx = xx + attn
        y = _layer_norm(xx, lp["ln2"]["scale"], lp["ln2"]["bias"],
                        cfg.layer_norm_eps)
        xx = xx + _mlp(y, lp, cfg, rules)
        return xx, (k_lane, v_lane)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    if adapters is None:
        logits = _finish(params, x, cfg, rules)  # [slots, 1, V]
    else:
        logits = _finish_adapter(params, x, adapters, slot_adapters, cfg,
                                 rules)
    return {"k": new_k, "v": new_v}, logits[:, 0].astype(jnp.float32)


# ---------------------------------------------------------------- paged
#
# vLLM-style paged layout (docs/serving.md "Paged KV & prefix caching"):
# the cache is a block pool `[L, pool_blocks, block_size, H, Dh]` and each
# sequence owns an ordered block table mapping logical block i → a pool
# block. The LAST pool block is the trash block: padded/inactive writes
# land there so they can never corrupt an owned block, and inactive slots
# point their whole table at it. Prefix caching falls out of the layout —
# a shared prompt's blocks appear in many tables at once (refcounted by
# the host BlockManager), and prefill only computes the novel suffix.


def init_paged_cache(
    cfg: Config, pool_blocks: int, block_size: int, dtype: Any = None
) -> Dict[str, jax.Array]:
    """Zeroed paged KV pool: {"k","v"}: [L, pool_blocks, bs, H, Dh].

    `pool_blocks` INCLUDES the trailing trash block (callers size it as
    `num_blocks + 1`)."""
    dt = dtype or cfg.dtype
    shape = (cfg.n_layer, pool_blocks, block_size, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def paged_cache_bytes(cfg: Config, pool_blocks: int, block_size: int,
                      dtype: Any = None) -> int:
    """HBM footprint of the paged pool (both K and V)."""
    dt = jnp.dtype(dtype or cfg.dtype)
    per = cfg.n_layer * pool_blocks * block_size * cfg.n_head * cfg.head_dim
    return 2 * per * dt.itemsize


def paged_prefill(
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,       # [bucket] int32: the NOVEL SUFFIX, right-padded
    suffix_len: jax.Array,   # scalar int32: real suffix length (<= bucket)
    prefix_len: jax.Array,   # scalar int32: tokens already cached (KV reuse)
    block_table: jax.Array,  # [max_blocks] int32: the sequence's table
    cfg: Config,
    rules: Optional[LogicalRules] = None,
    adapters: Optional[jax.Array] = None,   # [A+1, V, D] stack
    slot_adapter: Optional[jax.Array] = None,  # scalar int32 stack index
) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """Prefill the suffix `tokens[prefix_len:]` of a prompt whose first
    `prefix_len` tokens' K/V already sit in `block_table`'s blocks.

    Returns (cache', last-position logits [vocab]). The suffix K/V are
    scattered into the pool per the table, then the suffix queries attend
    over the gathered lane (cached prefix + just-written suffix). With
    `prefix_len == 0` this is a full prefill — same executable.
    """
    s = tokens.shape[0]
    mb = block_table.shape[0]
    bs = cache["k"].shape[2]
    trash = cache["k"].shape[1] - 1
    dt = cfg.dtype
    if adapters is None:
        x = _embed_tokens(params, tokens[None], cfg, rules, dt)
    else:
        x = _embed_adapter(adapters, slot_adapter, tokens, dt)[None]
    # Absolute positions prefix_len + i (clip keeps padded lanes in-table;
    # their queries are garbage the `last` index never selects).
    pos_ids = jnp.minimum(prefix_len + jnp.arange(s),
                          params["wpe"].shape[0] - 1)
    x = x + jnp.take(params["wpe"].astype(dt), pos_ids, axis=0)[None]
    x = shard_logical(x, ("batch", "seq", "embed"), rules)
    # Scatter destinations: real suffix positions land in their table
    # block; padded positions land in the trash block.
    dest_blk = jnp.where(jnp.arange(s) < suffix_len,
                         block_table[jnp.minimum(pos_ids // bs, mb - 1)],
                         trash)
    dest_off = pos_ids % bs
    # Causal mask over the gathered lane: key j visible to suffix query i
    # iff j <= prefix_len + i (prefix + suffix written so far + self).
    mask = jnp.arange(mb * bs)[None, :] <= (prefix_len + jnp.arange(s))[:, None]
    scale = 1.0 / math.sqrt(cfg.head_dim)

    def body(carry, layer_in):
        xx = carry
        lp, k_pool, v_pool = layer_in
        y = _layer_norm(xx, lp["ln1"]["scale"], lp["ln1"]["bias"],
                        cfg.layer_norm_eps)
        q, k, v = _qkv(y, lp, cfg)  # [1, S, H, Dh]
        k_pool = k_pool.at[dest_blk, dest_off].set(k[0].astype(k_pool.dtype))
        v_pool = v_pool.at[dest_blk, dest_off].set(v[0].astype(v_pool.dtype))
        k_lane = k_pool[block_table].reshape(mb * bs, cfg.n_head,
                                             cfg.head_dim)
        v_lane = v_pool[block_table].reshape(mb * bs, cfg.n_head,
                                             cfg.head_dim)
        logits = jnp.einsum("bshd,mhd->bhsm", q, k_lane).astype(jnp.float32)
        logits = jnp.where(mask[None, None], logits * scale,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhsm,mhd->bshd", probs, v_lane)
        attn = attn.reshape(xx.shape)
        attn = (jnp.einsum("bsd,de->bse", attn,
                           lp["attn_out"]["kernel"].astype(dt))
                + lp["attn_out"]["bias"].astype(dt))
        xx = xx + attn
        y = _layer_norm(xx, lp["ln2"]["scale"], lp["ln2"]["bias"],
                        cfg.layer_norm_eps)
        xx = xx + _mlp(y, lp, cfg, rules)
        xx = shard_logical(xx, ("batch", "seq", "embed"), rules)
        return xx, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    if adapters is None:
        logits = _finish(params, x, cfg, rules)  # [1, S, V]
    else:
        logits = _finish_adapter(params, x, adapters, slot_adapter, cfg,
                                 rules)
    last = jax.lax.dynamic_index_in_dim(
        logits[0], jnp.maximum(suffix_len - 1, 0), axis=0, keepdims=False)
    return {"k": new_k, "v": new_v}, last.astype(jnp.float32)


def paged_decode_step(
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,        # [slots] int32: last emitted token per slot
    positions: jax.Array,     # [slots] int32: index this step writes at
    block_tables: jax.Array,  # [slots, max_blocks] int32
    cfg: Config,
    rules: Optional[LogicalRules] = None,
    attention_impl: str = "reference",
    adapters: Optional[jax.Array] = None,      # [A+1, V, D] stack
    slot_adapters: Optional[jax.Array] = None,  # [slots] int32 stack index
) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """One paged decode step for every slot → (cache', logits [slots, V]).

    The dense decode's lane write/attend becomes a block write (table
    lookup of `position // block_size`) + a block-table-gathered
    attention (ops/paged_attention). Inactive slots write the trash block
    and attend garbage the batcher discards — zero recompiles to join or
    retire, exactly like the dense path.
    """
    from determined_tpu.ops.paged_attention import paged_decode_attention

    slots = tokens.shape[0]
    bs = cache["k"].shape[2]
    mb = block_tables.shape[1]
    dt = cfg.dtype
    if adapters is None:
        x = _embed_tokens(params, tokens[:, None], cfg, rules, dt)
    else:
        x = _embed_adapter(adapters, slot_adapters, tokens, dt)[:, None]
    pos_emb = jnp.take(params["wpe"].astype(dt), positions, axis=0)
    x = x + pos_emb[:, None]
    x = shard_logical(x, ("batch", "seq", "embed"), rules)
    wblk = jnp.take_along_axis(
        block_tables, jnp.minimum(positions // bs, mb - 1)[:, None],
        axis=1)[:, 0]  # [slots]
    woff = positions % bs

    def body(carry, layer_in):
        xx = carry  # [slots, 1, D]
        lp, k_pool, v_pool = layer_in
        y = _layer_norm(xx, lp["ln1"]["scale"], lp["ln1"]["bias"],
                        cfg.layer_norm_eps)
        q, k, v = _qkv(y, lp, cfg)  # [slots, 1, H, Dh]
        k_pool = k_pool.at[wblk, woff].set(k[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[wblk, woff].set(v[:, 0].astype(v_pool.dtype))
        attn = paged_decode_attention(
            q[:, 0], k_pool, v_pool, block_tables, positions,
            impl=attention_impl)  # [slots, H, Dh]
        attn = attn.reshape(slots, 1, -1)
        attn = (jnp.einsum("bsd,de->bse", attn,
                           lp["attn_out"]["kernel"].astype(dt))
                + lp["attn_out"]["bias"].astype(dt))
        xx = xx + attn
        y = _layer_norm(xx, lp["ln2"]["scale"], lp["ln2"]["bias"],
                        cfg.layer_norm_eps)
        xx = xx + _mlp(y, lp, cfg, rules)
        return xx, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    if adapters is None:
        logits = _finish(params, x, cfg, rules)  # [slots, 1, V]
    else:
        logits = _finish_adapter(params, x, adapters, slot_adapters, cfg,
                                 rules)
    return {"k": new_k, "v": new_v}, logits[:, 0].astype(jnp.float32)


def copy_paged_block(
    cache: Dict[str, jax.Array], dst: jax.Array, src: jax.Array
) -> Dict[str, jax.Array]:
    """Copy-on-write: duplicate pool block `src` into `dst` across every
    layer (both K and V). Used when a sequence must write into a block
    whose content is shared with other sequences (prefix caching)."""
    return {
        "k": cache["k"].at[:, dst].set(cache["k"][:, src]),
        "v": cache["v"].at[:, dst].set(cache["v"][:, src]),
    }


def sample(
    logits: jax.Array,        # [slots, vocab] fp32
    temperature: jax.Array,   # [slots] fp32; 0 = greedy
    rng: jax.Array,
) -> jax.Array:
    """Next token per slot: greedy at temperature 0, else categorical."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    drawn = jax.random.categorical(rng, logits / temp, axis=-1).astype(
        jnp.int32)
    return jnp.where(temperature > 0, drawn, greedy)
