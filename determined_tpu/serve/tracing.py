"""Per-request span tracing for serve replicas (docs/observability.md
"Request spans", docs/serving.md "Request latency & SLOs").

Every request that retires from the batcher yields a span tree on the
trace whose id IS the request id (minted/propagated as `X-Request-Id` by
the master router; the root span's span_id == the request id, exactly the
trial.lifecycle convention):

  serve.request                      submit → finish (root, replica-side)
  ├── serve.queue_wait               submit → admission
  ├── serve.prefill                  bucket/suffix/prefix-hit/blocks attrs
  └── serve.decode                   tokens/steps/occupancy attrs

The master-side `serve.router.dispatch` span (replica chosen, retries,
breaker state) is recorded directly by the router into the same trace —
`GET /api/v1/deployments/{id}/requests/{rid}/trace` stitches both.

Sampling: errors and SLO breaches (`serving.slo_ms`) are ALWAYS traced;
everything else is traced at `serving.trace_sample` (default 1.0 — drop
it in production if the span volume matters). Spans buffer in memory and
batch-POST to `POST /api/v1/allocations/{id}/request_spans` off the
decode loop; a dead span sink drops the batch and never blocks or fails
a generation — the `serving.trace.drop` fault point (docs/chaos.md)
proves that path deterministically, same contract as `trace.span.drop`.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Any, Dict, List, Optional

from determined_tpu.common import faultpoint
from determined_tpu.common.trace import Span

logger = logging.getLogger("determined_tpu.serve")

FAULT_TRACE_DROP = "serving.trace.drop"

# Keep at most this many spans buffered when the sink is gone: tracing is
# best-effort by contract and must never become the replica's memory leak.
MAX_BUFFERED_SPANS = 4096


class RequestTracer:
    """Buffered request-span emitter for one serve replica.

    `record()` is called by the batcher at retire (its thread); `flush()`
    runs on the shipper thread (or inline in tests). Local/masterless mode
    (`session=None`) keeps everything in `local_spans` so the same
    instrumentation is inspectable without a cluster.
    """

    def __init__(
        self,
        session=None,
        allocation_id: str = "",
        sample: float = 1.0,
        slo_ms: Optional[float] = None,
        flush_period_s: float = 1.0,
    ):
        self._session = session
        self._allocation_id = allocation_id
        self.sample = min(1.0, max(0.0, float(sample)))
        self.slo_ms = float(slo_ms) if slo_ms else None
        self._period = max(0.1, float(flush_period_s))
        self._buf: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._rng = random.Random()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Observability of the tracer itself.
        self.recorded = 0   # requests that produced a span tree
        self.sampled_out = 0
        self.dropped = 0    # batches lost to sink failure / fault point
        self.slo_breaches = 0
        self.local_spans: List[Dict[str, Any]] = []

    # -- recording (batcher thread) ------------------------------------

    def _should_trace(self, req) -> bool:
        if req.error is not None:
            return True  # errors are always traced
        if self.slo_ms is not None and req.finished_us and req.submitted_us:
            if (req.finished_us - req.submitted_us) / 1e3 > self.slo_ms:
                self.slo_breaches += 1
                return True  # SLO breaches are always traced
        if self.sample >= 1.0:
            return True
        return self._rng.random() < self.sample

    def record(self, req) -> bool:
        """Build the request's span tree and buffer it. Returns True when
        the request was sampled in. Never raises past the batcher."""
        if not self._should_trace(req):
            self.sampled_out += 1
            return False
        spans = self._build_spans(req)
        with self._lock:
            self._buf.extend(spans)
            if len(self._buf) > MAX_BUFFERED_SPANS:
                overflow = len(self._buf) - MAX_BUFFERED_SPANS
                del self._buf[:overflow]
                self.dropped += 1
        self.recorded += 1
        return True

    def _build_spans(self, req) -> List[Dict[str, Any]]:
        rid = req.id
        end_us = req.finished_us or req.submitted_us

        def span(name, start, end, parent, attrs=None):
            sp = Span(rid, name, parent=parent, start_us=int(start),
                      attrs=attrs)
            sp.end_us = int(end)
            return sp

        # Root: span_id == trace_id == request id (the trial.lifecycle
        # convention) so the router's dispatch span parents to it without
        # any replica↔master coordination.
        # Version attrs (docs/serving.md "Model lifecycle"): which model
        # version this replica serves (DET_MODEL_VERSION, pinned by the
        # deployment controller at spawn) and which adapter the request
        # routed to — the trace answers "which weights answered this".
        import os as _os

        model_version = _os.environ.get("DET_MODEL_VERSION")
        root = span("serve.request", req.submitted_us, end_us, "", {
            "prompt_tokens": int(req.tokens.size),
            "new_tokens": len(req.out_tokens),
            **({"model_version": model_version} if model_version else {}),
            **({"model": req.model}
               if getattr(req, "model", None) else {}),
            **({"error": req.error} if req.error else {}),
        })
        root.span_id = rid
        out = [root.to_dict()]
        if req.admitted_us:
            out.append(span(
                "serve.queue_wait", req.submitted_us, req.admitted_us,
                rid).to_dict())
        if req.prefill_start_us:
            out.append(span(
                "serve.prefill", req.prefill_start_us,
                req.prefill_end_us or end_us, rid, {
                    "bucket": req.bucket,
                    "suffix_len": int(req.tokens.size) - req.cached_len,
                    "prefix_cache_hit": req.cached_len > 0,
                    "cached_len": req.cached_len,
                    "blocks": req.blocks_allocated,
                }).to_dict())
        if req.first_token_us and len(req.out_tokens) > 1:
            out.append(span(
                "serve.decode", req.first_token_us, end_us, rid, {
                    "tokens": len(req.out_tokens),
                    "steps": req.decode_steps,
                    "occupancy_at_admit": req.occupancy_at_admit,
                }).to_dict())
        return out

    # -- shipping ------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    def flush(self) -> int:
        """Ship the buffered batch. Never raises: span-sink loss must not
        reach a generation (the zero-failed-requests contract of
        `serving.trace.drop`). Returns spans shipped or locally kept."""
        with self._lock:
            if not self._buf:
                return 0
            batch, self._buf = self._buf, []
        if faultpoint.fire(FAULT_TRACE_DROP) is not faultpoint.Action.NONE:
            logger.warning("faultpoint dropped %d request span(s)",
                           len(batch))
            self.dropped += 1
            return 0
        if self._session is None or not self._allocation_id:
            self.local_spans.extend(batch)
            return len(batch)
        try:
            self._session.post(
                f"/api/v1/allocations/{self._allocation_id}/request_spans",
                body={"spans": batch})
            return len(batch)
        except Exception:
            self.dropped += 1
            logger.warning("request-span flush failed; dropped %d span(s)",
                           len(batch), exc_info=True)
            return 0

    def _run(self) -> None:
        while not self._stop_evt.wait(self._period):
            self.flush()
        self.flush()

    def start(self) -> "RequestTracer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="serve-trace")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.flush()

    def stats(self) -> Dict[str, Any]:
        return {
            "recorded": self.recorded,
            "sampled_out": self.sampled_out,
            "dropped_batches": self.dropped,
            "slo_breaches": self.slo_breaches,
            "pending": self.pending(),
            "sample": self.sample,
            "slo_ms": self.slo_ms,
        }
