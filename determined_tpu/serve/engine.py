"""ServingEngine — checkpoint → AOT-compiled prefill/decode executables.

Owns everything device-side for one serve replica:

  - loads a **COMPLETED** checkpoint through the integrity protocol
    (manifest + COMMIT verified before a single byte is trusted; a corrupt
    latest checkpoint falls back through the COMPLETED lineage exactly
    like `Trainer._restore`),
  - AOT-compiles the decode step once and the prefill step per prompt
    bucket (`jit(...).lower(...).compile()`), so no request ever pays a
    trace — the serving analogue of the trial preflight discipline:
    all compilation happens before the first request is admitted,
  - holds the slot-dense KV cache (donated through every call: one copy
    in HBM) and a step-folded sampling rng.

The engine is intentionally single-consumer: only the batcher thread
(scheduler.py) calls prefill/decode; stats reads are lock-free counters.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from determined_tpu import _jax_compat
from determined_tpu.models.gpt2 import Config
from determined_tpu.parallel.sharding import LogicalRules
from determined_tpu.serve import model as smodel

_jax_compat.install()

logger = logging.getLogger("determined_tpu.serve")

DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024)


def default_buckets(max_seq: int) -> List[int]:
    out = [b for b in DEFAULT_BUCKETS if b < max_seq]
    return out + [max_seq]


def load_checkpoint_params(
    checkpoint_ctx, storage_id: str, trial_id: Optional[int] = None
) -> Dict[str, Any]:
    """Verified params from a COMPLETED checkpoint (lineage fallback).

    `checkpoint_ctx` is a core CheckpointContext; `storage_id` may be
    "latest" (newest COMPLETED in the lineage). Integrity verification
    happens before restore; a corrupt candidate falls back through the
    COMPLETED lineage — serving a half-written model would be strictly
    worse than refusing to start.
    """
    from determined_tpu.core import CorruptCheckpoint

    candidates: List[str]
    if storage_id == "latest":
        candidates = checkpoint_ctx.lineage()
        if not candidates:
            raise FileNotFoundError(
                "serving.checkpoint=latest but the lineage has no "
                "COMPLETED checkpoint")
    else:
        candidates = [storage_id]
    last_err: Optional[Exception] = None
    for i, sid in enumerate(candidates):
        try:
            checkpoint_ctx.verify(sid)
            state = _restore_raw(checkpoint_ctx, sid)
            params = state.get("params") if isinstance(state, dict) else None
            if params is None:
                raise ValueError(
                    f"checkpoint {sid} has no 'params' subtree — not a "
                    "TrainState checkpoint")
            logger.info("serving params restored from checkpoint %s", sid)
            return params
        except (FileNotFoundError, CorruptCheckpoint) as e:
            last_err = e
            logger.warning("checkpoint %s unusable (%s); %s", sid, e,
                           "walking lineage back" if i + 1 < len(candidates)
                           else "lineage exhausted")
            if storage_id != "latest" and i == 0:
                # Explicit id failed: extend with the lineage behind it.
                candidates.extend(
                    c for c in checkpoint_ctx.lineage() if c != sid)
    raise last_err if last_err is not None else FileNotFoundError(storage_id)


def _restore_raw(checkpoint_ctx, storage_id: str) -> Any:
    """Whole-tree restore without a template (serving has no optimizer, so
    it cannot reconstruct the TrainState template the trainer restores
    into; orbax rebuilds the saved structure from checkpoint metadata)."""
    import os

    import orbax.checkpoint as ocp

    path = checkpoint_ctx._array_path(storage_id)
    state_dir = path + "/state" if "://" in path else os.path.join(
        path, "state")
    return ocp.StandardCheckpointer().restore(state_dir)


def resolve_attention_impl(impl: str) -> str:
    """serving.attention_impl → the engine's concrete path.

    "auto" picks the Pallas kernel on TPU and the jnp gather reference
    elsewhere (both paged); "pallas"/"reference"/"dense" force a path —
    off-TPU the kernel runs through pallas interpret mode (tier-1)."""
    import jax

    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "reference"
    if impl in ("pallas", "reference", "dense"):
        return impl
    raise ValueError(
        f"unknown serving.attention_impl {impl!r}; "
        "valid: auto, pallas, reference, dense")


class ServingEngine:
    """Compiled prefill/decode over a fixed slot batch + KV cache.

    The cache is paged by default (docs/serving.md "Paged KV & prefix
    caching"): a block pool `[L, num_blocks + 1, block_size, H, Dh]`
    (the extra block is the trash block for padded/inactive writes) plus
    per-slot block tables the batcher hands in at prefill. Every
    executable takes the table as an input canonicalized to the full
    `max_seq_len // block_size` length, so ONE decode executable and one
    prefill executable per token bucket cover every table — joining,
    retiring and prefix sharing never recompile. `attention_impl:
    dense` keeps the legacy slot-dense lane layout for A/B benching.
    """

    def __init__(
        self,
        params: Dict[str, Any],
        cfg: Config,
        *,
        slots: int = 8,
        max_seq_len: int = 256,
        prefill_buckets: Optional[Sequence[int]] = None,
        rules: Optional[LogicalRules] = None,
        seed: int = 0,
        attention_impl: str = "auto",
        kv_block_size: int = 16,
        kv_num_blocks: Optional[int] = None,
        adapters: Optional[Dict[str, Any]] = None,
    ):
        import jax
        import jax.numpy as jnp

        if slots <= 0:
            raise ValueError("slots must be positive")
        self.cfg = cfg
        self.slots = slots
        self.max_seq_len = min(max_seq_len, cfg.n_positions)
        buckets = sorted(set(
            min(b, self.max_seq_len)
            for b in (prefill_buckets or default_buckets(self.max_seq_len))))
        self.prefill_buckets = buckets
        self.rules = rules or LogicalRules()
        self.params = jax.device_put(params)
        # Multi-adapter serving (docs/serving.md "Model lifecycle"):
        # adapter name → params tree of a head-tuned fine-tune. Only the
        # (tied) embedding/LM-head table participates: the stack
        # [A+1, V, D] (index 0 = base) rides every compiled call and a
        # per-slot index selects each lane's table — one executable, one
        # KV pool, N fine-tunes. The transformer body stays the base's;
        # an adapter checkpoint whose body drifted from the base would
        # serve the base body silently, so we refuse anything but an
        # exact wte-shape match and document the contract.
        self.adapter_ids: Dict[str, int] = {"base": 0}
        self._adapter_stack = None
        self._slot_adapters = None
        if adapters:
            base_wte = self.params["wte"]
            tables = [base_wte]
            for name, tree in adapters.items():
                wte = tree.get("wte") if isinstance(tree, dict) else None
                if wte is None:
                    raise ValueError(
                        f"adapter {name!r}: checkpoint has no 'wte' table")
                if tuple(wte.shape) != tuple(base_wte.shape):
                    raise ValueError(
                        f"adapter {name!r}: wte shape {tuple(wte.shape)} "
                        f"!= base {tuple(base_wte.shape)} — adapters must "
                        "share the base model's geometry")
                self.adapter_ids[name] = len(tables)
                tables.append(jnp.asarray(wte, base_wte.dtype))
            self._adapter_stack = jax.device_put(jnp.stack(tables))
            self._slot_adapters = np.zeros((slots,), np.int32)
        self.attention_impl = resolve_attention_impl(attention_impl)
        self.paged = self.attention_impl != "dense"
        self.block_size = int(kv_block_size)
        self.num_blocks = int(kv_num_blocks) if kv_num_blocks else 0
        self._check_geometry()
        self._cache = None  # materialized at compile() (geometry may move)
        self._tables = None  # host [slots, max_blocks] int32, paged only
        self._rng = jax.random.PRNGKey(seed)
        self._step_counter = 0
        self._compiled_decode = None
        self._compiled_prefill: Dict[int, Any] = {}
        self._compiled_sample = None
        self._compiled_copy_block = None
        self.compile_stats: Dict[str, float] = {}
        # Warm-AOT provenance (docs/serving.md "Scale to zero"): how this
        # engine got its executables — "deserialize" when every piece came
        # from the compile-farm artifact store (a scale-from-zero cold
        # start that never re-traced), "mixed" for a partial hit, "trace"
        # for a cold compile.
        self.aot_source = "trace"
        # device-call counters (drained into /v1/stats)
        self.decode_steps = 0
        self.prefills = 0
        self.block_copies = 0

    # -- paged geometry ------------------------------------------------

    def _check_geometry(self) -> None:
        if not self.paged:
            return
        if self.max_seq_len % self.block_size != 0:
            raise ValueError(
                f"kv_block_size {self.block_size} must divide max_seq_len "
                f"{self.max_seq_len} (preflight rule DTL206)")
        if not self.num_blocks:
            self.num_blocks = self.slots * (
                self.max_seq_len // self.block_size)
        # A pool smaller than one max_seq sequence is legal here (tests
        # build tiny backpressure pools); configs are gated by DTL206,
        # and the batcher rejects any request the pool can never cover.

    # -- adapters ------------------------------------------------------

    @property
    def has_adapters(self) -> bool:
        return self._adapter_stack is not None

    @property
    def adapter_names(self) -> List[str]:
        return [n for n in self.adapter_ids if n != "base"]

    def adapter_index(self, name: Optional[str]) -> int:
        """Stack index for a per-request `model:` name; '' / None /
        'base' = the base checkpoint. Unknown names raise ValueError —
        the HTTP front-end turns that into a 400, never a silent
        base-model answer the caller did not ask for."""
        if not name or name == "base":
            return 0
        idx = self.adapter_ids.get(name)
        if idx is None:
            raise ValueError(
                f"unknown adapter {name!r}; resident: "
                f"{self.adapter_names or '(none)'}")
        return idx

    def set_slot_adapter(self, slot: int, adapter: int) -> None:
        if self._slot_adapters is not None:
            self._slot_adapters[slot] = adapter

    @property
    def max_blocks_per_seq(self) -> int:
        return self.max_seq_len // self.block_size

    @property
    def trash_block(self) -> int:
        """Pool index of the write sink for padded/inactive lanes."""
        return self.num_blocks

    def set_block_geometry(self, block_size: int,
                           num_blocks: int) -> None:
        """Sync the device pool to an external BlockManager's geometry
        (the batcher calls this before compile so the tables it hands
        out index the real pool)."""
        if not self.paged:
            return
        if (self._compiled_decode is not None
                and (block_size != self.block_size
                     or num_blocks != self.num_blocks)):
            raise RuntimeError(
                "engine already compiled with block geometry "
                f"{self.num_blocks}x{self.block_size}; cannot switch to "
                f"{num_blocks}x{block_size}")
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self._check_geometry()

    def cache_hbm_bytes(self) -> int:
        """HBM the KV cache occupies (the admission budget's anchor)."""
        if self.paged:
            return smodel.paged_cache_bytes(
                self.cfg, self.num_blocks + 1, self.block_size)
        return smodel.cache_bytes(self.cfg, self.slots, self.max_seq_len)

    # -- compilation ---------------------------------------------------

    def compile(self, farm=None) -> Dict[str, float]:
        """AOT-compile decode + every prefill bucket + the sampler.

        Runs before the HTTP front-end admits anything, so request latency
        never includes a trace/compile (and a config the model can't
        compile fails the replica at startup, not mid-traffic).

        With a `farm` (compile.runtime.FarmClient scoped to the serving
        signature), each executable is first warm-loaded from the PR-9
        artifact store — node-local AOT dir, then master — and only
        compiled when no artifact exists; fresh compiles are saved back
        (locally and uploaded) so the NEXT cold start deserializes in
        tens of milliseconds instead of tracing. This is what makes a
        scale-from-zero respawn fit inside cold_start_budget_s.
        """
        import jax

        from determined_tpu.compile import runtime as _crt

        fresh_artifacts: Dict[str, bytes] = {}
        hits = misses = 0

        def acquire(key, build):
            """Farm-load executable `key` or compile it fresh (queuing the
            serialized result for save-back). Farm failures degrade to the
            plain compile — the farm is an accelerator, not a dependency."""
            nonlocal hits, misses
            if farm is not None:
                loaded = farm.load_executable(key)
                if loaded is not None:
                    hits += 1
                    self.compile_stats[f"{key}_source"] = "deserialize"
                    return loaded
            compiled = build()
            misses += 1
            if farm is not None:
                self.compile_stats[f"{key}_source"] = "trace"
                try:
                    fresh_artifacts[_crt.aot_artifact_name(key)] = \
                        _crt.serialize_compiled(compiled)
                except Exception:
                    logger.debug("serve AOT serialize failed for %s", key,
                                 exc_info=True)
            return compiled

        t_all = time.monotonic()
        cfg, rules = self.cfg, self.rules
        if self._cache is None:
            if self.paged:
                self._cache = smodel.init_paged_cache(
                    cfg, self.num_blocks + 1, self.block_size)
                self._tables = np.full(
                    (self.slots, self.max_blocks_per_seq),
                    self.trash_block, np.int32)
            else:
                self._cache = smodel.init_cache(
                    cfg, self.slots, self.max_seq_len)
        sds = jax.ShapeDtypeStruct
        cache_sd = jax.tree_util.tree_map(
            lambda x: sds(x.shape, x.dtype), self._cache)
        params_sd = jax.tree_util.tree_map(
            lambda x: sds(x.shape, x.dtype), self.params)
        i32, f32 = np.int32, np.float32
        mb = self.max_blocks_per_seq
        impl = self.attention_impl

        # Adapter stack aval (multi-adapter replicas): every decode/
        # prefill executable takes the [A+1, V, D] table stack plus the
        # per-lane index as INPUTS — adapter routing changes operands,
        # never executables, so N fine-tunes share one compile.
        stack_sd = None
        if self.has_adapters:
            stack_sd = sds(self._adapter_stack.shape,
                           self._adapter_stack.dtype)

        t0 = time.monotonic()
        if self.paged:
            def build_decode():
                if stack_sd is not None:
                    decode = jax.jit(
                        lambda p, c, t, pos, tbl, ad, sa:
                            smodel.paged_decode_step(
                                p, c, t, pos, tbl, cfg, rules,
                                attention_impl=impl, adapters=ad,
                                slot_adapters=sa),
                        donate_argnums=(1,))
                    return decode.lower(
                        params_sd, cache_sd, sds((self.slots,), i32),
                        sds((self.slots,), i32), sds((self.slots, mb), i32),
                        stack_sd, sds((self.slots,), i32)).compile()
                decode = jax.jit(
                    lambda p, c, t, pos, tbl: smodel.paged_decode_step(
                        p, c, t, pos, tbl, cfg, rules, attention_impl=impl),
                    donate_argnums=(1,))
                return decode.lower(
                    params_sd, cache_sd, sds((self.slots,), i32),
                    sds((self.slots,), i32),
                    sds((self.slots, mb), i32)).compile()
        else:
            def build_decode():
                if stack_sd is not None:
                    decode = jax.jit(
                        lambda p, c, t, pos, ad, sa: smodel.decode_step(
                            p, c, t, pos, cfg, rules, adapters=ad,
                            slot_adapters=sa),
                        donate_argnums=(1,))
                    return decode.lower(
                        params_sd, cache_sd, sds((self.slots,), i32),
                        sds((self.slots,), i32), stack_sd,
                        sds((self.slots,), i32)).compile()
                decode = jax.jit(
                    lambda p, c, t, pos: smodel.decode_step(
                        p, c, t, pos, cfg, rules),
                    donate_argnums=(1,))
                return decode.lower(
                    params_sd, cache_sd,
                    sds((self.slots,), i32), sds((self.slots,), i32)).compile()
        self._compiled_decode = acquire("decode", build_decode)
        self.compile_stats["decode_s"] = round(time.monotonic() - t0, 3)

        for bucket in self.prefill_buckets:
            t0 = time.monotonic()
            if self.paged:
                def build_prefill(bucket=bucket):
                    if stack_sd is not None:
                        pf = jax.jit(
                            lambda p, c, t, ln, pfx, tbl, ad, sa:
                                smodel.paged_prefill(
                                    p, c, t, ln, pfx, tbl, cfg, rules,
                                    adapters=ad, slot_adapter=sa),
                            donate_argnums=(1,))
                        return pf.lower(
                            params_sd, cache_sd, sds((bucket,), i32),
                            sds((), i32), sds((), i32), sds((mb,), i32),
                            stack_sd, sds((), i32)).compile()
                    pf = jax.jit(
                        lambda p, c, t, ln, pfx, tbl: smodel.paged_prefill(
                            p, c, t, ln, pfx, tbl, cfg, rules),
                        donate_argnums=(1,))
                    return pf.lower(
                        params_sd, cache_sd, sds((bucket,), i32),
                        sds((), i32), sds((), i32), sds((mb,), i32)).compile()
            else:
                def build_prefill(bucket=bucket):
                    if stack_sd is not None:
                        pf = jax.jit(
                            lambda p, c, t, ln, sl, ad, sa: smodel.prefill(
                                p, c, t, ln, sl, cfg, rules, adapters=ad,
                                slot_adapter=sa),
                            donate_argnums=(1,))
                        return pf.lower(
                            params_sd, cache_sd, sds((bucket,), i32),
                            sds((), i32), sds((), i32), stack_sd,
                            sds((), i32)).compile()
                    pf = jax.jit(
                        lambda p, c, t, ln, sl: smodel.prefill(
                            p, c, t, ln, sl, cfg, rules),
                        donate_argnums=(1,))
                    return pf.lower(
                        params_sd, cache_sd, sds((bucket,), i32),
                        sds((), i32), sds((), i32)).compile()
            self._compiled_prefill[bucket] = acquire(
                f"prefill_{bucket}", build_prefill)
            self.compile_stats[f"prefill_{bucket}_s"] = round(
                time.monotonic() - t0, 3)

        if self.paged:
            t0 = time.monotonic()

            def build_copy():
                cp = jax.jit(smodel.copy_paged_block, donate_argnums=(0,))
                return cp.lower(
                    cache_sd, sds((), i32), sds((), i32)).compile()
            self._compiled_copy_block = acquire("copy_block", build_copy)
            self.compile_stats["copy_block_s"] = round(
                time.monotonic() - t0, 3)

        t0 = time.monotonic()

        def build_sample():
            sample = jax.jit(smodel.sample)
            return sample.lower(
                sds((self.slots, cfg.vocab_size), f32),
                sds((self.slots,), f32),
                sds((2,), np.uint32)).compile()
        self._compiled_sample = acquire("sample", build_sample)
        self.compile_stats["sample_s"] = round(time.monotonic() - t0, 3)
        self.compile_stats["total_s"] = round(time.monotonic() - t_all, 3)
        if hits > 0:
            self.aot_source = "deserialize" if misses == 0 else "mixed"
        else:
            self.aot_source = "trace"
        self.compile_stats["aot_hits"] = hits
        self.compile_stats["aot_misses"] = misses
        if farm is not None and fresh_artifacts:
            # Save-back off the serving path: node-local first (the next
            # respawn on this node needs no master), then the farm store.
            farm.save_local(fresh_artifacts)
            farm.upload_async(
                fresh_artifacts,
                compile_ms=self.compile_stats["total_s"] * 1e3)
        logger.info("serving engine compiled (%s): %s", self.aot_source,
                    self.compile_stats)
        return dict(self.compile_stats)

    def bucket_for(self, length: int) -> Optional[int]:
        """Smallest compiled prefill bucket covering `length`; None when
        the prompt exceeds every bucket (reject at admission)."""
        for b in self.prefill_buckets:
            if length <= b:
                return b
        return None

    # -- device calls (batcher thread only) ----------------------------

    def _next_rng(self):
        import jax

        self._step_counter += 1
        return jax.random.fold_in(self._rng, self._step_counter)

    def _default_table(self, slot: int, n_blocks: int) -> list:
        """Static per-slot partition for direct engine use (no external
        BlockManager): slot i owns pool blocks [i*mb, (i+1)*mb)."""
        mb = self.max_blocks_per_seq
        if (slot + 1) * mb > self.num_blocks:
            raise ValueError(
                f"pool of {self.num_blocks} blocks cannot statically "
                f"partition slot {slot}; pass an explicit block_table")
        return list(range(slot * mb, slot * mb + n_blocks))

    def copy_block(self, src: int, dst: int) -> None:
        """Copy-on-write device copy: pool block `src` → `dst` across all
        layers (both K and V). The BlockManager decides WHEN (a shared
        block is about to be written); this mirrors it on-device."""
        if not self.paged:
            raise RuntimeError("copy_block requires the paged cache")
        if self._compiled_decode is None:
            self.compile()
        self._cache = self._compiled_copy_block(
            self._cache, np.int32(dst), np.int32(src))
        self.block_copies += 1

    def prefill_request(self, slot: int, tokens: np.ndarray,
                        temperature: float = 0.0,
                        block_table: Optional[Sequence[int]] = None,
                        cached_len: int = 0, adapter: int = 0) -> int:
        """Prefill `tokens` into the slot's cache; returns the first
        generated token. Compiled-bucket dispatch by NOVEL length: with
        `cached_len > 0` (prefix-cache hit) only the suffix
        `tokens[cached_len:]` runs through the model — the bucket, and
        therefore the prefill cost, shrinks to the novel part. `adapter`
        selects the slot's table from the adapter stack (0 = base); the
        slot keeps it for every decode step until release."""
        if self._compiled_decode is None:
            self.compile()
        if adapter and not self.has_adapters:
            raise ValueError("engine has no adapters resident")
        self.set_slot_adapter(slot, adapter)
        length = int(tokens.shape[0])
        if not self.paged:
            if cached_len:
                raise ValueError(
                    "prefix caching requires the paged cache layout")
            bucket = self.bucket_for(length)
            if bucket is None:
                raise ValueError(
                    f"prompt length {length} exceeds the largest prefill "
                    f"bucket ({self.prefill_buckets[-1]})")
            padded = np.zeros((bucket,), np.int32)
            padded[:length] = tokens
            args = [self.params, self._cache, padded,
                    np.int32(length), np.int32(slot)]
            if self.has_adapters:
                args += [self._adapter_stack, np.int32(adapter)]
            self._cache, logits = self._compiled_prefill[bucket](*args)
            self.prefills += 1
            return self._sample_first(logits, temperature)
        if not 0 <= cached_len < length:
            raise ValueError(
                f"cached_len {cached_len} must leave >= 1 novel token "
                f"of the {length}-token prompt")
        mb = self.max_blocks_per_seq
        if block_table is None:
            # Direct engine use (no BlockManager): the slot's whole
            # static partition, so decode can grow past the prompt.
            block_table = self._default_table(slot, mb)
        table = np.full((mb,), self.trash_block, np.int32)
        table[:min(len(block_table), mb)] = list(block_table)[:mb]
        suffix = np.asarray(tokens, np.int32)[cached_len:]
        s_len = int(suffix.shape[0])
        bucket = self.bucket_for(s_len)
        if bucket is None:
            raise ValueError(
                f"suffix length {s_len} exceeds the largest prefill "
                f"bucket ({self.prefill_buckets[-1]})")
        padded = np.zeros((bucket,), np.int32)
        padded[:s_len] = suffix
        args = [self.params, self._cache, padded,
                np.int32(s_len), np.int32(cached_len), table]
        if self.has_adapters:
            args += [self._adapter_stack, np.int32(adapter)]
        self._cache, logits = self._compiled_prefill[bucket](*args)
        self._tables[slot] = table
        self.prefills += 1
        return self._sample_first(logits, temperature)

    def _sample_first(self, logits, temperature: float) -> int:
        """Sample via the slot-wide compiled sampler (slot 0 carries the
        logits; the rest are padding lanes)."""
        batch = np.zeros((self.slots, self.cfg.vocab_size), np.float32)
        batch[0] = np.asarray(logits, np.float32)
        temps = np.zeros((self.slots,), np.float32)
        temps[0] = temperature
        toks = self._compiled_sample(batch, temps, self._next_rng())
        return int(np.asarray(toks)[0])

    def release_slot(self, slot: int) -> None:
        """Point a retired slot's table at the trash block so later
        decode steps can never touch its (possibly reallocated) blocks,
        and hand the lane's adapter back to base."""
        if self.paged and self._tables is not None:
            self._tables[slot] = self.trash_block
        self.set_slot_adapter(slot, 0)

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               temperatures: np.ndarray) -> np.ndarray:
        """One decode step for all slots → sampled next tokens [slots].

        Paged mode feeds the per-slot block tables recorded at prefill
        (they only change at admission/CoW, both of which happen at step
        boundaries in the batcher thread)."""
        if self._compiled_decode is None:
            self.compile()
        args = [self.params, self._cache, np.asarray(tokens, np.int32),
                np.asarray(positions, np.int32)]
        if self.paged:
            args.append(self._tables)
        if self.has_adapters:
            args += [self._adapter_stack, self._slot_adapters.copy()]
        self._cache, logits = self._compiled_decode(*args)
        toks = self._compiled_sample(
            logits, np.asarray(temperatures, np.float32), self._next_rng())
        self.decode_steps += 1
        return np.asarray(toks)

    def stats(self) -> Dict[str, Any]:
        return {
            "slots": self.slots,
            "adapters": self.adapter_names,
            "max_seq_len": self.max_seq_len,
            "prefill_buckets": list(self.prefill_buckets),
            "attention_impl": self.attention_impl,
            "kv_layout": "paged" if self.paged else "dense",
            "kv_block_size": self.block_size if self.paged else None,
            "kv_num_blocks": self.num_blocks if self.paged else None,
            "cache_hbm_bytes": self.cache_hbm_bytes(),
            "decode_steps": self.decode_steps,
            "prefills": self.prefills,
            "block_copies": self.block_copies,
            "compile": dict(self.compile_stats),
        }
