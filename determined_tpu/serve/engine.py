"""ServingEngine — checkpoint → AOT-compiled prefill/decode executables.

Owns everything device-side for one serve replica:

  - loads a **COMPLETED** checkpoint through the integrity protocol
    (manifest + COMMIT verified before a single byte is trusted; a corrupt
    latest checkpoint falls back through the COMPLETED lineage exactly
    like `Trainer._restore`),
  - AOT-compiles the decode step once and the prefill step per prompt
    bucket (`jit(...).lower(...).compile()`), so no request ever pays a
    trace — the serving analogue of the trial preflight discipline:
    all compilation happens before the first request is admitted,
  - holds the slot-dense KV cache (donated through every call: one copy
    in HBM) and a step-folded sampling rng.

The engine is intentionally single-consumer: only the batcher thread
(scheduler.py) calls prefill/decode; stats reads are lock-free counters.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from determined_tpu import _jax_compat
from determined_tpu.models.gpt2 import Config
from determined_tpu.parallel.sharding import LogicalRules
from determined_tpu.serve import model as smodel

_jax_compat.install()

logger = logging.getLogger("determined_tpu.serve")

DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024)


def default_buckets(max_seq: int) -> List[int]:
    out = [b for b in DEFAULT_BUCKETS if b < max_seq]
    return out + [max_seq]


def load_checkpoint_params(
    checkpoint_ctx, storage_id: str, trial_id: Optional[int] = None
) -> Dict[str, Any]:
    """Verified params from a COMPLETED checkpoint (lineage fallback).

    `checkpoint_ctx` is a core CheckpointContext; `storage_id` may be
    "latest" (newest COMPLETED in the lineage). Integrity verification
    happens before restore; a corrupt candidate falls back through the
    COMPLETED lineage — serving a half-written model would be strictly
    worse than refusing to start.
    """
    from determined_tpu.core import CorruptCheckpoint

    candidates: List[str]
    if storage_id == "latest":
        candidates = checkpoint_ctx.lineage()
        if not candidates:
            raise FileNotFoundError(
                "serving.checkpoint=latest but the lineage has no "
                "COMPLETED checkpoint")
    else:
        candidates = [storage_id]
    last_err: Optional[Exception] = None
    for i, sid in enumerate(candidates):
        try:
            checkpoint_ctx.verify(sid)
            state = _restore_raw(checkpoint_ctx, sid)
            params = state.get("params") if isinstance(state, dict) else None
            if params is None:
                raise ValueError(
                    f"checkpoint {sid} has no 'params' subtree — not a "
                    "TrainState checkpoint")
            logger.info("serving params restored from checkpoint %s", sid)
            return params
        except (FileNotFoundError, CorruptCheckpoint) as e:
            last_err = e
            logger.warning("checkpoint %s unusable (%s); %s", sid, e,
                           "walking lineage back" if i + 1 < len(candidates)
                           else "lineage exhausted")
            if storage_id != "latest" and i == 0:
                # Explicit id failed: extend with the lineage behind it.
                candidates.extend(
                    c for c in checkpoint_ctx.lineage() if c != sid)
    raise last_err if last_err is not None else FileNotFoundError(storage_id)


def _restore_raw(checkpoint_ctx, storage_id: str) -> Any:
    """Whole-tree restore without a template (serving has no optimizer, so
    it cannot reconstruct the TrainState template the trainer restores
    into; orbax rebuilds the saved structure from checkpoint metadata)."""
    import os

    import orbax.checkpoint as ocp

    path = checkpoint_ctx._array_path(storage_id)
    state_dir = path + "/state" if "://" in path else os.path.join(
        path, "state")
    return ocp.StandardCheckpointer().restore(state_dir)


class ServingEngine:
    """Compiled prefill/decode over a fixed slot batch + KV cache."""

    def __init__(
        self,
        params: Dict[str, Any],
        cfg: Config,
        *,
        slots: int = 8,
        max_seq_len: int = 256,
        prefill_buckets: Optional[Sequence[int]] = None,
        rules: Optional[LogicalRules] = None,
        seed: int = 0,
    ):
        import jax

        if slots <= 0:
            raise ValueError("slots must be positive")
        self.cfg = cfg
        self.slots = slots
        self.max_seq_len = min(max_seq_len, cfg.n_positions)
        buckets = sorted(set(
            min(b, self.max_seq_len)
            for b in (prefill_buckets or default_buckets(self.max_seq_len))))
        self.prefill_buckets = buckets
        self.rules = rules or LogicalRules()
        self.params = jax.device_put(params)
        self._cache = smodel.init_cache(cfg, slots, self.max_seq_len)
        self._rng = jax.random.PRNGKey(seed)
        self._step_counter = 0
        self._compiled_decode = None
        self._compiled_prefill: Dict[int, Any] = {}
        self._compiled_sample = None
        self.compile_stats: Dict[str, float] = {}
        # device-call counters (drained into /v1/stats)
        self.decode_steps = 0
        self.prefills = 0

    # -- compilation ---------------------------------------------------

    def compile(self) -> Dict[str, float]:
        """AOT-compile decode + every prefill bucket + the sampler.

        Runs before the HTTP front-end admits anything, so request latency
        never includes a trace/compile (and a config the model can't
        compile fails the replica at startup, not mid-traffic).
        """
        import jax

        t_all = time.monotonic()
        cfg, rules = self.cfg, self.rules
        sds = jax.ShapeDtypeStruct
        cache_sd = jax.tree_util.tree_map(
            lambda x: sds(x.shape, x.dtype), self._cache)
        params_sd = jax.tree_util.tree_map(
            lambda x: sds(x.shape, x.dtype), self.params)
        i32, f32 = np.int32, np.float32

        t0 = time.monotonic()
        decode = jax.jit(
            lambda p, c, t, pos: smodel.decode_step(p, c, t, pos, cfg, rules),
            donate_argnums=(1,))
        self._compiled_decode = decode.lower(
            params_sd, cache_sd,
            sds((self.slots,), i32), sds((self.slots,), i32)).compile()
        self.compile_stats["decode_s"] = round(time.monotonic() - t0, 3)

        for bucket in self.prefill_buckets:
            t0 = time.monotonic()
            pf = jax.jit(
                lambda p, c, t, ln, sl: smodel.prefill(
                    p, c, t, ln, sl, cfg, rules),
                donate_argnums=(1,))
            self._compiled_prefill[bucket] = pf.lower(
                params_sd, cache_sd, sds((bucket,), i32),
                sds((), i32), sds((), i32)).compile()
            self.compile_stats[f"prefill_{bucket}_s"] = round(
                time.monotonic() - t0, 3)

        t0 = time.monotonic()
        sample = jax.jit(smodel.sample)
        self._compiled_sample = sample.lower(
            sds((self.slots, cfg.vocab_size), f32),
            sds((self.slots,), f32),
            sds((2,), np.uint32)).compile()
        self.compile_stats["sample_s"] = round(time.monotonic() - t0, 3)
        self.compile_stats["total_s"] = round(time.monotonic() - t_all, 3)
        logger.info("serving engine compiled: %s", self.compile_stats)
        return dict(self.compile_stats)

    def bucket_for(self, length: int) -> Optional[int]:
        """Smallest compiled prefill bucket covering `length`; None when
        the prompt exceeds every bucket (reject at admission)."""
        for b in self.prefill_buckets:
            if length <= b:
                return b
        return None

    # -- device calls (batcher thread only) ----------------------------

    def _next_rng(self):
        import jax

        self._step_counter += 1
        return jax.random.fold_in(self._rng, self._step_counter)

    def prefill_request(self, slot: int, tokens: np.ndarray,
                        temperature: float = 0.0) -> int:
        """Prefill `tokens` into cache lane `slot`; returns the first
        generated token. Compiled-bucket dispatch by prompt length."""
        if self._compiled_decode is None:
            self.compile()
        length = int(tokens.shape[0])
        bucket = self.bucket_for(length)
        if bucket is None:
            raise ValueError(
                f"prompt length {length} exceeds the largest prefill "
                f"bucket ({self.prefill_buckets[-1]})")
        padded = np.zeros((bucket,), np.int32)
        padded[:length] = tokens
        self._cache, logits = self._compiled_prefill[bucket](
            self.params, self._cache, padded,
            np.int32(length), np.int32(slot))
        self.prefills += 1
        # Sample via the slot-wide compiled sampler (slot 0 carries the
        # logits; the rest are padding lanes).
        batch = np.zeros((self.slots, self.cfg.vocab_size), np.float32)
        batch[0] = np.asarray(logits, np.float32)
        temps = np.zeros((self.slots,), np.float32)
        temps[0] = temperature
        toks = self._compiled_sample(batch, temps, self._next_rng())
        return int(np.asarray(toks)[0])

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               temperatures: np.ndarray) -> np.ndarray:
        """One decode step for all slots → sampled next tokens [slots]."""
        if self._compiled_decode is None:
            self.compile()
        self._cache, logits = self._compiled_decode(
            self.params, self._cache,
            np.asarray(tokens, np.int32), np.asarray(positions, np.int32))
        toks = self._compiled_sample(
            logits, np.asarray(temperatures, np.float32), self._next_rng())
        self.decode_steps += 1
        return np.asarray(toks)

    def stats(self) -> Dict[str, Any]:
        return {
            "slots": self.slots,
            "max_seq_len": self.max_seq_len,
            "prefill_buckets": list(self.prefill_buckets),
            "decode_steps": self.decode_steps,
            "prefills": self.prefills,
            "compile": dict(self.compile_stats),
        }
