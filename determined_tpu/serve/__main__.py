"""`python -m determined_tpu.serve` — alias for the serve task entrypoint."""

import sys

from determined_tpu.serve.task import main

if __name__ == "__main__":
    sys.exit(main())
