"""Continuous token-level batcher + bounded admission queue.

The serving analogue of the training input pipeline's producer/consumer
discipline (data/prefetch.py): the HTTP front-end *produces* requests into
a bounded queue (backpressure, never unbounded growth), and a single
batcher thread *consumes* them into the decode loop — the device never
waits on request plumbing, and request plumbing never races the device.

The batching contract (Orca/vLLM-style continuous batching):

  - **join at step boundaries**: new sequences are admitted (prefilled
    into a free slot + KV blocks reserved) only between decode steps —
    never mid-step, so running sequences see zero jitter from joins;
  - **retire without drain**: a sequence that finishes frees its slot and
    KV blocks immediately; remaining sequences keep decoding and the next
    queued request joins at the very next boundary — the batch never
    drains to refill;
  - **drain semantics** (spot preemption / shutdown): `drain()` stops
    admissions at the front door (submit raises Draining → HTTP 503) but
    every accepted request — queued or mid-decode — still completes: an
    accepted request is a promise (the zero-dropped-responses contract of
    docs/cluster-ops.md's drain lifecycle).

Chaos: `serving.request.drop` fires in submit() (docs/chaos.md) — drop
sheds the request as if the queue were full; error fails the submit.

Observability (docs/serving.md "Request latency & SLOs"): every request
records wall-clock phase timestamps (submitted → admitted → prefill →
first token → finished) so retire can fold it into the token-latency
histograms — TTFT, TPOT (inter-token), e2e, queue wait — and hand it to
an attached RequestTracer (serve/tracing.py) for the per-request span
tree. Both are retire-time work: the decode loop itself never touches a
clock beyond the per-step timestamps it already takes.
"""

from __future__ import annotations

import collections
import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from determined_tpu.common import faultpoint
from determined_tpu.serve.kv_cache import BlockManager

logger = logging.getLogger("determined_tpu.serve")

FAULT_POINT_DROP = "serving.request.drop"

_req_counter = itertools.count()


def now_us() -> int:
    """Wall-clock epoch microseconds — the span time domain shared with
    the master router's dispatch spans (common/trace.py now_us)."""
    return int(time.time() * 1e6)


# Shared bucket boundaries (seconds) for every serving latency histogram.
# The replica heartbeat ships them with the counts, so the master's
# aggregation and `det_serve_request_seconds` exposition can never drift
# from the replica's binning.
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class LatencyHist:
    """Fixed-bucket latency histogram (cumulative counts, Prometheus `le`
    semantics — the Python twin of the master's Hist struct)."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets=LATENCY_BUCKETS_S):
        self.buckets = tuple(buckets)
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, seconds: float) -> None:
        seconds = max(0.0, float(seconds))
        for i, le in enumerate(self.buckets):
            if seconds <= le:
                self.counts[i] += 1
        self.sum += seconds
        self.count += 1

    def percentile(self, q: float) -> float:
        """Quantile estimate in seconds, linearly interpolated inside the
        winning bucket (histogram_quantile style). 0 when empty; the last
        boundary when the quantile lands in the +Inf bucket."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        prev_le, prev_c = 0.0, 0
        for le, c in zip(self.buckets, self.counts):
            if c >= target:
                span = c - prev_c
                frac = (target - prev_c) / span if span > 0 else 1.0
                return prev_le + (le - prev_le) * frac
            prev_le, prev_c = le, c
        return self.buckets[-1]

    def summary(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean_ms": round(self.sum / self.count * 1e3, 3)
            if self.count else 0.0,
            "p50_ms": round(self.percentile(0.5) * 1e3, 3),
            "p99_ms": round(self.percentile(0.99) * 1e3, 3),
        }

    def to_wire(self) -> Dict[str, Any]:
        """Heartbeat form: boundaries + cumulative counts, mergeable
        master-side by summing counts across replicas."""
        return {
            "le": list(self.buckets),
            "counts": list(self.counts),
            "sum": round(self.sum, 6),
            "count": self.count,
        }


class QueueFull(RuntimeError):
    """Admission queue at capacity — retry later (HTTP 429/503)."""


class Draining(RuntimeError):
    """Replica is draining — no new admissions (HTTP 503 + retry)."""


class Request:
    """One generation request: prompt tokens in, generated tokens out."""

    def __init__(
        self,
        tokens,
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        request_id: Optional[str] = None,
        model: Optional[str] = None,
    ):
        self.id = request_id or f"req-{next(_req_counter)}"
        # Per-request adapter routing (docs/serving.md "Model
        # lifecycle"): which resident fine-tune serves this request;
        # None/"base" = the base checkpoint.
        self.model = model or None
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        if self.tokens.size == 0:
            raise ValueError("prompt must contain at least one token")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.out_tokens: List[int] = []
        self.submitted_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.error: Optional[str] = None
        self._done = threading.Event()
        # Wall-clock phase stamps (epoch µs, the span time domain): set by
        # the batcher as the request moves submit → admit → prefill →
        # first token → finish. Consumed at retire by the latency
        # histograms and the RequestTracer's span tree.
        self.submitted_us = now_us()
        self.admitted_us = 0
        self.prefill_start_us = 0
        self.prefill_end_us = 0
        self.first_token_us = 0
        self.finished_us = 0
        # Trace attributes recorded at admission (serve.prefill /
        # serve.decode span attrs).
        self.bucket = 0               # prefill bucket chosen (suffix len)
        self.cached_len = 0           # prefix-cache hit depth in tokens
        self.blocks_allocated = 0     # KV blocks charged at admission
        self.occupancy_at_admit = 0   # active slots when this one joined
        self.decode_steps = 0         # decode steps this request rode

    @property
    def total_budget(self) -> int:
        """Worst-case KV footprint in tokens (prompt + every new token)."""
        return int(self.tokens.size) + self.max_new_tokens

    def _finish(self, error: Optional[str] = None,
                notify: bool = True) -> None:
        self.error = error
        self.finished_at = time.monotonic()
        self.finished_us = now_us()
        # notify=False lets the batcher observe latency + spans BEFORE
        # waiters wake: by the time the HTTP response leaves, the
        # request's trace and histogram entries exist (tests and the
        # drain's final flush rely on that ordering).
        if notify:
            self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Block until the request completes; raises on failure/timeout."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not finished")
        if self.error is not None:
            raise RuntimeError(f"request {self.id} failed: {self.error}")
        latency_ms = (self.finished_at - self.submitted_at) * 1e3
        queue_ms = ((self.admitted_at or self.finished_at)
                    - self.submitted_at) * 1e3
        out = {
            "id": self.id,
            "tokens": list(self.out_tokens),
            "prompt_tokens": int(self.tokens.size),
            "latency_ms": round(latency_ms, 3),
            "queue_ms": round(queue_ms, 3),
        }
        if self.first_token_us:
            out["ttft_ms"] = round(
                (self.first_token_us - self.submitted_us) / 1e3, 3)
            if len(self.out_tokens) > 1 and self.finished_us:
                out["tpot_ms"] = round(
                    (self.finished_us - self.first_token_us) / 1e3
                    / (len(self.out_tokens) - 1), 3)
        return out


class AdmissionQueue:
    """Bounded FIFO between the front-end and the batcher.

    submit() applies backpressure (QueueFull) instead of buffering
    unboundedly, and refuses outright while draining — the two failure
    modes a load balancer can act on (retry elsewhere vs back off).
    """

    def __init__(self, maxsize: int = 64):
        self.maxsize = max(1, int(maxsize))
        self._dq: "collections.deque[Request]" = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._draining = False
        self.rejected_full = 0
        self.rejected_draining = 0
        self.dropped = 0  # serving.request.drop shed count

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        with self._lock:
            return len(self._dq)

    def submit(self, req: Request) -> Request:
        action = faultpoint.fire(FAULT_POINT_DROP)
        if action is faultpoint.Action.ERROR:
            raise faultpoint.FaultInjected(FAULT_POINT_DROP)
        with self._lock:
            if self._draining:
                self.rejected_draining += 1
                raise Draining("replica is draining; not admitting")
            if action is faultpoint.Action.DROP:
                self.dropped += 1
                raise QueueFull("request shed (serving.request.drop)")
            if len(self._dq) >= self.maxsize:
                self.rejected_full += 1
                raise QueueFull(
                    f"admission queue at capacity ({self.maxsize})")
            self._dq.append(req)
            self._nonempty.notify_all()
        return req

    def peek(self) -> Optional[Request]:
        with self._lock:
            return self._dq[0] if self._dq else None

    def pop(self) -> Optional[Request]:
        with self._lock:
            return self._dq.popleft() if self._dq else None

    def wait_nonempty(self, timeout: float) -> bool:
        with self._lock:
            if self._dq:
                return True
            self._nonempty.wait(timeout)
            return bool(self._dq)

    def drain(self) -> None:
        with self._lock:
            self._draining = True
            self._nonempty.notify_all()

    def undrain(self) -> None:
        with self._lock:
            self._draining = False


class _Slot:
    __slots__ = ("req", "position", "last_token")

    def __init__(self, req: Request, position: int, last_token: int):
        self.req = req
        self.position = position  # index the NEXT decode step writes at
        self.last_token = last_token


class ContinuousBatcher:
    """The decode loop: admit → step → retire, forever.

    Owns the engine's host-side slot state and the KV block accounting.
    `events` records (kind, request_id, step) tuples — ("admit"/"retire"
    at the boundary they happened) — so tests can assert the
    join-at-boundary / retire-without-drain ordering directly.
    """

    def __init__(
        self,
        engine,
        queue: Optional[AdmissionQueue] = None,
        block_manager: Optional[BlockManager] = None,
        idle_wait_s: float = 0.02,
    ):
        self.engine = engine
        self.queue = queue or AdmissionQueue()
        bm = block_manager
        paged = getattr(engine, "paged", False)
        if bm is None:
            if paged:
                # Mirror the engine's device pool exactly: tables the
                # manager hands out index real pool blocks.
                bm = BlockManager(num_blocks=engine.num_blocks,
                                  block_size=engine.block_size)
            else:
                # Dense layout: accounting-only pool sized to the cache
                # (slots lanes of max_seq tokens).
                bm = BlockManager(
                    num_blocks=engine.slots * max(
                        1, engine.max_seq_len // 16), block_size=16)
        elif paged:
            # An external manager defines the geometry; sync the device
            # pool to it before compile() freezes the executables.
            engine.set_block_geometry(bm.block_size, bm.num_blocks)
        self.blocks = bm
        self._idle_wait = idle_wait_s
        self._slots: List[Optional[_Slot]] = [None] * engine.slots
        self._stop_evt = threading.Event()
        self._drained_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # events/counters only
        self.events: List[Tuple[str, str, int]] = []
        self.steps = 0
        self.active_steps = 0      # steps with >= 1 active slot
        self.occupancy_sum = 0     # sum of active slots over active steps
        self.max_occupancy = 0
        self.completed = 0
        self.generated_tokens = 0
        self.failed = 0
        # Per-adapter admission counts ("base" + each resident fine-tune)
        # — the multi-tenant visibility knob on /v1/stats.
        self.adapter_requests: Dict[str, int] = {}
        # EWMA of admit→finish seconds, updated at retire: the basis of
        # the computed Retry-After hint (429s carry an actionable backoff
        # instead of a bare "1"; the master router propagates it).
        self._service_s_ewma = 0.0
        # Token-latency SLO histograms (docs/serving.md "Request latency
        # & SLOs"), observed once per request at retire — exposed on
        # /v1/stats, /metrics, and the master heartbeat.
        self.ttft_hist = LatencyHist()        # submit → first token
        self.tpot_hist = LatencyHist()        # mean inter-token interval
        self.e2e_hist = LatencyHist()         # submit → finished
        self.queue_wait_hist = LatencyHist()  # submit → admitted
        # Optional per-request span tracer (serve/tracing.py), attached by
        # the task entrypoint / tests; None = no request tracing.
        self.tracer = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        if self._thread is not None:
            return self
        # AOT everything before the first admit; an attached FarmClient
        # (engine.farm, set by the task entrypoint) warm-loads executables
        # from the PR-9 artifact store instead of tracing — the
        # scale-from-zero cold-start path (docs/serving.md).
        self.engine.compile(farm=getattr(self.engine, "farm", None))
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-batcher")
        self._thread.start()
        return self

    def submit(self, req: Request) -> Request:
        # Validate against engine limits at the front door — a prompt no
        # bucket covers would otherwise poison the batcher thread.
        if req.model is not None:
            # Unknown adapter names 400 here, not in the batcher thread —
            # and never silently fall back to the base model.
            self.engine.adapter_index(req.model)
        if self.engine.bucket_for(int(req.tokens.size)) is None:
            raise ValueError(
                f"prompt length {req.tokens.size} exceeds the largest "
                f"prefill bucket ({self.engine.prefill_buckets[-1]})")
        if req.total_budget > self.engine.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {req.total_budget} exceeds "
                f"max_seq_len ({self.engine.max_seq_len})")
        if self.blocks.blocks_for_tokens(req.total_budget) > \
                self.blocks.num_blocks:
            raise ValueError(
                f"prompt + max_new_tokens = {req.total_budget} exceeds the "
                f"KV pool ({self.blocks.num_blocks} x "
                f"{self.blocks.block_size}-token blocks) — the request "
                "could never be admitted")
        return self.queue.submit(req)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting; wait for queued + in-flight work to finish.

        Returns True when fully drained within `timeout` (None = just
        signal, don't wait)."""
        self.queue.drain()
        if timeout is None:
            return self.idle()
        return self._drained_evt.wait(timeout)

    def stop(self, timeout: float = 10.0) -> None:
        """Hard stop: fail outstanding requests and join the thread."""
        self._stop_evt.set()
        self.queue.drain()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        for slot in self._slots:
            if slot is not None and not slot.req.done():
                slot.req._finish("batcher stopped")
        while True:
            req = self.queue.pop()
            if req is None:
                break
            req._finish("batcher stopped")

    def idle(self) -> bool:
        return self.queue.depth() == 0 and all(
            s is None for s in self._slots)

    def active_count(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    # -- the loop ------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop_evt.is_set():
                self._admit()
                active = [i for i, s in enumerate(self._slots)
                          if s is not None]
                if not active:
                    if self.queue.draining and self.queue.depth() == 0:
                        self._drained_evt.set()
                        if self._stop_evt.wait(self._idle_wait):
                            return
                        continue
                    self.queue.wait_nonempty(self._idle_wait)
                    continue
                self._drained_evt.clear()
                self._step(active)
        except BaseException as e:  # noqa: BLE001 — fail open requests
            logger.exception("batcher loop failed")
            msg = f"{type(e).__name__}: {e}"
            for slot in self._slots:
                if slot is not None:
                    slot.req._finish(msg)
                    self.failed += 1
            self._slots = [None] * self.engine.slots
            while True:
                req = self.queue.pop()
                if req is None:
                    break
                req._finish(msg)
                self.failed += 1
            self._drained_evt.set()

    def _admit(self) -> None:
        """Join queued requests at this step boundary while a free slot
        AND enough KV blocks exist (block exhaustion keeps the request
        queued — backpressure, not failure).

        Paged engines admit through BlockManager.admit: a prompt whose
        prefix is cached reuses those blocks (refcounted) and is charged
        only its novel suffix — prefill then runs only that suffix."""
        paged = getattr(self.engine, "paged", False)
        while True:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                return
            req = self.queue.peek()
            if req is None:
                return
            cached_len = 0
            cow_pairs = ()
            if paged:
                admitted = self.blocks.admit(
                    req.id, req.tokens.tolist(), req.total_budget)
                if admitted is None:
                    return  # pool exhausted: wait for a retire
                table, cached_len, cow_pairs = admitted
            else:
                table = self.blocks.allocate(req.id, req.total_budget)
                if table is None:
                    return  # pool exhausted: wait for a retire
            popped = self.queue.pop()
            assert popped is req, "single-consumer queue invariant"
            slot_id = free[0]
            req.admitted_at = time.monotonic()
            req.admitted_us = now_us()
            req.cached_len = cached_len
            req.occupancy_at_admit = self.engine.slots - len(free) + 1
            req.blocks_allocated = (
                len(table) if paged
                else self.blocks.blocks_for_tokens(req.total_budget))
            req.bucket = self.engine.bucket_for(
                int(req.tokens.size) - cached_len) or 0
            req.prefill_start_us = req.admitted_us
            try:
                # Adapter routing: resolve the request's `model:` name to
                # its stack index (0 = base). Validated at submit; a
                # request that snuck past still fails HERE as a per-
                # request error, never a batcher crash.
                adapter = self.engine.adapter_index(req.model)
                # Device-side copy-on-write BEFORE any write can land in
                # a block other sequences still reference.
                for src, dst in cow_pairs:
                    self.engine.copy_block(src, dst)
                if paged:
                    first = self.engine.prefill_request(
                        slot_id, req.tokens, req.temperature,
                        block_table=table, cached_len=cached_len,
                        adapter=adapter)
                else:
                    first = self.engine.prefill_request(
                        slot_id, req.tokens, req.temperature,
                        adapter=adapter)
            except Exception as e:
                # discard=True: the blocks' K/V were never (fully)
                # written; they must not linger in the prefix cache.
                self.blocks.free(req.id, discard=True)
                req._finish(f"prefill failed: {type(e).__name__}: {e}",
                            notify=False)
                self.failed += 1
                self._observe_finished(req)
                req._done.set()
                continue
            req.prefill_end_us = req.first_token_us = now_us()
            req.out_tokens.append(first)
            with self._lock:
                self.events.append(("admit", req.id, self.steps))
                name = req.model or "base"
                self.adapter_requests[name] = \
                    self.adapter_requests.get(name, 0) + 1
            self.generated_tokens += 1
            if self._finished(req, first):
                self._retire(slot_id, req, admitted_only=True)
                continue
            self._slots[slot_id] = _Slot(
                req, position=int(req.tokens.size), last_token=first)

    def _step(self, active: List[int]) -> None:
        slots = self.engine.slots
        tokens = np.zeros((slots,), np.int32)
        positions = np.zeros((slots,), np.int32)
        temps = np.zeros((slots,), np.float32)
        for i in active:
            s = self._slots[i]
            tokens[i] = s.last_token
            positions[i] = s.position
            temps[i] = s.req.temperature
        next_tokens = self.engine.decode(tokens, positions, temps)
        with self._lock:
            self.steps += 1
            self.active_steps += 1
            self.occupancy_sum += len(active)
            self.max_occupancy = max(self.max_occupancy, len(active))
        for i in active:
            s = self._slots[i]
            tok = int(next_tokens[i])
            s.req.out_tokens.append(tok)
            s.req.decode_steps += 1
            self.generated_tokens += 1
            s.position += 1
            s.last_token = tok
            if self._finished(s.req, tok):
                self._retire(i, s.req)

    @staticmethod
    def _finished(req: Request, token: int) -> bool:
        return (len(req.out_tokens) >= req.max_new_tokens
                or (req.eos_id is not None and token == req.eos_id))

    def _retire(self, slot_id: int, req: Request,
                admitted_only: bool = False) -> None:
        """Free the slot + KV blocks and complete the request — the rest
        of the batch keeps decoding (no drain)."""
        if not admitted_only:
            self._slots[slot_id] = None
        # Paged: the retired slot keeps riding the decode batch as an
        # inactive lane (position 0); its table must point at the trash
        # block so that lane's dead write can never land in a block the
        # pool hands to the next sequence.
        release = getattr(self.engine, "release_slot", None)
        if release is not None:
            release(slot_id)
        self.blocks.free(req.id)
        req._finish(notify=False)
        with self._lock:
            self.events.append(("retire", req.id, self.steps))
            self.completed += 1
            if req.admitted_at is not None:
                service_s = max(0.0, req.finished_at - req.admitted_at)
                alpha = 0.2
                self._service_s_ewma = (
                    service_s if self._service_s_ewma == 0.0
                    else alpha * service_s
                    + (1 - alpha) * self._service_s_ewma)
        self._observe_finished(req)
        req._done.set()

    def _observe_finished(self, req: Request) -> None:
        """Retire-time observability: fold the request into the latency
        histograms and hand it to the tracer (which samples + buffers;
        span-sink loss can never reach the decode loop)."""
        with self._lock:
            self.e2e_hist.observe(
                (req.finished_us - req.submitted_us) / 1e6)
            if req.admitted_us:
                self.queue_wait_hist.observe(
                    (req.admitted_us - req.submitted_us) / 1e6)
            if req.first_token_us:
                self.ttft_hist.observe(
                    (req.first_token_us - req.submitted_us) / 1e6)
                if len(req.out_tokens) > 1 and req.finished_us:
                    self.tpot_hist.observe(
                        (req.finished_us - req.first_token_us) / 1e6
                        / (len(req.out_tokens) - 1))
        tracer = self.tracer
        if tracer is not None:
            try:
                tracer.record(req)
            except Exception:
                logger.warning("request tracer failed", exc_info=True)

    # -- stats ---------------------------------------------------------

    def retry_after_hint(self) -> int:
        """Seconds a 429'd client should wait before retrying: the time
        until a queue slot plausibly frees, from the queue depth and the
        smoothed per-request service time spread over the batch slots.
        Clamped to [1, 60] so a cold or idle replica still answers 1."""
        with self._lock:
            service = self._service_s_ewma
        depth = self.queue.depth()
        if service <= 0.0 or depth <= 0:
            return 1
        est = depth * service / max(1, self.engine.slots)
        return max(1, min(60, int(est + 0.999)))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            occ = (self.occupancy_sum / self.active_steps
                   if self.active_steps else 0.0)
            return {
                "queue_depth": self.queue.depth(),
                "queue_capacity": self.queue.maxsize,
                "draining": self.queue.draining,
                "active": self.active_count(),
                "slots": self.engine.slots,
                "steps": self.steps,
                "mean_occupancy": round(occ, 3),
                "max_occupancy": self.max_occupancy,
                "completed": self.completed,
                "failed": self.failed,
                "generated_tokens": self.generated_tokens,
                "rejected_full": self.queue.rejected_full,
                "rejected_draining": self.queue.rejected_draining,
                "dropped": self.queue.dropped,
                "adapter_requests": dict(self.adapter_requests),
                "kv_blocks": self.blocks.stats(),
                "latency": {
                    "ttft": self.ttft_hist.summary(),
                    "tpot": self.tpot_hist.summary(),
                    "e2e": self.e2e_hist.summary(),
                    "queue_wait": self.queue_wait_hist.summary(),
                },
            }

    def heartbeat_stats(self) -> Dict[str, Any]:
        """The load-report subset pushed to the master on the replica
        heartbeat (POST /allocations/{id}/serve_stats): the router's
        least-loaded signal and the deployment autoscaler's input."""
        kv = self.blocks.stats()
        with self._lock:
            latency = {
                "ttft": self.ttft_hist.to_wire(),
                "tpot": self.tpot_hist.to_wire(),
                "e2e": self.e2e_hist.to_wire(),
                "queue_wait": self.queue_wait_hist.to_wire(),
            }
        return {
            "queue_depth": self.queue.depth(),
            "queue_capacity": self.queue.maxsize,
            "active": self.active_count(),
            "slots": self.engine.slots,
            "kv_blocks_free": kv.get("free_blocks", 0),
            "kv_blocks_used": kv.get("used_blocks", 0),
            "kv_blocks_total": kv.get("num_blocks", 0),
            "prefix_cache_hit_rate": kv.get("prefix_cache_hit_rate", 0.0),
            "draining": self.queue.draining,
            "retry_after_hint_s": self.retry_after_hint(),
            # Warm-AOT provenance: "deserialize" proves a cold start
            # restored executables instead of tracing (the master's
            # serve.cold_start span resurfaces it).
            "engine_source": getattr(self.engine, "aot_source", "trace"),
            # Mergeable latency histograms (boundaries + cumulative
            # counts): the master sums counts across fresh replicas into
            # the per-deployment p50/p99 on the deployment APIs and the
            # det_serve_request_seconds{deployment=...} exposition.
            "latency": latency,
        }
