"""`det serve` — high-throughput inference serving from trained checkpoints.

The subsystem that takes the platform past the checkpoint (ROADMAP item 2):
a SERVING task type loads a COMPLETED, integrity-verified checkpoint,
AOT-compiles bucketed prefill + single-token decode executables, and runs
continuous token-level batching — sequences join at decode-step boundaries
and retire without draining the batch, behind a bounded admission queue.

Layout:
  model.py      KV-cached GPT-2 prefill/decode steps (shape-static, AOT);
                paged block-pool variants (paged_prefill/paged_decode_step)
  kv_cache.py   KV block manager: paged admission accounting, refcounted
                prefix caching, copy-on-write
  engine.py     checkpoint loading + compiled executables + device state
                (paged pool + block tables by default; dense kept for A/B)
  scheduler.py  bounded admission queue + the continuous batcher
  http.py       HTTP front-end (generate/stats/health)
  task.py       cluster entrypoint (drain lifecycle, proxy registration)

The paged decode-attention kernel itself lives in
determined_tpu/ops/paged_attention.py (docs/serving.md "Paged KV &
prefix caching").

Docs: docs/serving.md.
"""

from determined_tpu.serve.engine import ServingEngine, load_checkpoint_params
from determined_tpu.serve.kv_cache import BlockManager, KVBlockError
from determined_tpu.serve.scheduler import (
    AdmissionQueue,
    ContinuousBatcher,
    Draining,
    QueueFull,
    Request,
)

__all__ = [
    "AdmissionQueue",
    "BlockManager",
    "ContinuousBatcher",
    "Draining",
    "KVBlockError",
    "QueueFull",
    "Request",
    "ServingEngine",
    "load_checkpoint_params",
]
