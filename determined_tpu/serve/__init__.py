"""`det serve` — high-throughput inference serving from trained checkpoints.

The subsystem that takes the platform past the checkpoint (ROADMAP item 2):
a SERVING task type loads a COMPLETED, integrity-verified checkpoint,
AOT-compiles bucketed prefill + single-token decode executables, and runs
continuous token-level batching — sequences join at decode-step boundaries
and retire without draining the batch, behind a bounded admission queue.

Layout:
  model.py      KV-cached GPT-2 prefill/decode steps (shape-static, AOT)
  kv_cache.py   host-side KV block manager (admission accounting)
  engine.py     checkpoint loading + compiled executables + device state
  scheduler.py  bounded admission queue + the continuous batcher
  http.py       HTTP front-end (generate/stats/health)
  task.py       cluster entrypoint (drain lifecycle, proxy registration)

Docs: docs/serving.md.
"""

from determined_tpu.serve.engine import ServingEngine, load_checkpoint_params
from determined_tpu.serve.kv_cache import BlockManager, KVBlockError
from determined_tpu.serve.scheduler import (
    AdmissionQueue,
    ContinuousBatcher,
    Draining,
    QueueFull,
    Request,
)

__all__ = [
    "AdmissionQueue",
    "BlockManager",
    "ContinuousBatcher",
    "Draining",
    "KVBlockError",
    "QueueFull",
    "Request",
    "ServingEngine",
    "load_checkpoint_params",
]
