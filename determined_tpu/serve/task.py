"""`det serve` task entrypoint — one serve replica.

Launched by the master as a SERVING task (`python3 -m
determined_tpu.serve.task`; config travels in DET_SERVING_CONFIG), or
locally via `det serve <config> --local`. Lifecycle:

  1. build the model config (`serving.model` / `serving.model_config`),
  2. load + integrity-verify a COMPLETED checkpoint (engine.py),
  3. AOT-compile prefill buckets + decode, start the batcher + HTTP
     front-end, report the proxy address to the master,
  4. long-poll the allocation preemption signal (the same channel trials
     use, core/_preempt.py): on a drain — spot notice, maintenance,
     scheduler preemption — stop admitting, finish every accepted
     request inside the grace window, and exit 0 so the master
     reschedules the replica on surviving capacity
     (docs/cluster-ops.md "Preemption & drain lifecycle").

SIGTERM gets the same drain treatment, so `det deploy local down` and
plain kills are graceful too.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import sys
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("determined_tpu.serve")

DRAIN_SAFETY_MARGIN_S = 2.0
HEARTBEAT_PERIOD_S = 2.0


class ReplicaHeartbeat:
    """Pushes the replica's load report (queue depth, occupancy, KV
    blocks, drain state) to the master on a fixed period — the router's
    least-loaded signal and the deployment autoscaler's input
    (docs/serving.md "Deployments & autoscaling"). Loss-tolerant: a
    failed POST is logged and the next beat retries; the master treats
    stale reports as "no signal", never as "dead"."""

    def __init__(self, session, allocation_id: str, batcher,
                 period_s: float = HEARTBEAT_PERIOD_S):
        self._session = session
        self._allocation_id = allocation_id
        self._batcher = batcher
        self._period = max(0.2, float(period_s))
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """One synchronous report. Called by the loop, and directly at
        drain start (the drain handshake: the master must see
        draining=true before the grace window burns down, so the router
        ejects the replica immediately rather than at the next period)."""
        if self._session is None or not self._allocation_id:
            return
        try:
            stats = self._batcher.heartbeat_stats()
            # Model-lifecycle confirmation (docs/serving.md "Model
            # lifecycle"): echo the version label the master pinned at
            # spawn — the deployment detail shows what each replica
            # ACTUALLY serves, not only what the controller intended.
            mv = os.environ.get("DET_MODEL_VERSION")
            if mv:
                stats["model_version"] = mv
            adapters = getattr(self._batcher.engine, "adapter_names", None)
            if adapters:
                stats["adapters"] = list(adapters)
            self._session.post(
                f"/api/v1/allocations/{self._allocation_id}/serve_stats",
                body=stats)
        except Exception:
            logger.debug("serve_stats heartbeat failed", exc_info=True)

    def _run(self) -> None:
        while not self._stop_evt.wait(self._period):
            self.beat()

    def start(self) -> "ReplicaHeartbeat":
        if self._session is None or not self._allocation_id:
            return self  # local/masterless mode: nothing to report to
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def build_model(serving: Dict[str, Any]):
    """serving.model/model_config → a models/* Config (gpt2 family)."""
    import jax.numpy as jnp

    from determined_tpu.models import gpt2

    family = serving.get("model", "gpt2")
    if family != "gpt2":
        raise ValueError(
            f"unknown serving.model {family!r}; supported: gpt2")
    mc = dict(serving.get("model_config") or {})
    size = mc.get("model_size", "small")
    base = {
        "tiny": gpt2.Config.tiny,
        "small": gpt2.Config.small,
        "medium": gpt2.Config.medium,
        "large": gpt2.Config.large,
    }[size]()
    dtypes = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}
    seq_len = int(mc.get("seq_len", base.n_positions))
    # Every architecture dim is overridable: the config must reproduce
    # the trained checkpoint's exact shapes or the engine's first trace
    # fails loudly at startup (the intended failure mode for a mismatch).
    return gpt2.Config(
        vocab_size=int(mc.get("vocab_size", base.vocab_size)),
        n_positions=max(int(mc.get("n_positions", base.n_positions)),
                        seq_len),
        d_model=int(mc.get("d_model", base.d_model)),
        n_layer=int(mc.get("n_layer", base.n_layer)),
        n_head=int(mc.get("n_head", base.n_head)),
        dtype=dtypes[mc.get("dtype", "bfloat16")],
        attention_impl="dot",  # decode attends over the KV cache directly
        num_experts=int(mc.get("num_experts", 1)),
        moe_top_k=int(mc.get("moe_top_k", 2)),
    )


def serving_signature(serving: Dict[str, Any]) -> str:
    """Compile-farm signature for a serving config: every shape-affecting
    knob (model geometry, slots, buckets, paged-KV layout) plus the
    runtime tag, so two replicas of the same deployment — or a respawn
    after scale-to-zero — address the same AOT artifacts, and a config
    change can never load a stale executable."""
    import hashlib

    from determined_tpu.compile.signature import runtime_tag

    shape_keys = ("model", "model_config", "max_batch_size", "max_seq_len",
                  "kv_block_size", "kv_num_blocks", "prefill_buckets",
                  "attention_impl", "seed", "adapters")
    key = {k: serving.get(k) for k in shape_keys}
    key["runtime_tag"] = runtime_tag()
    blob = json.dumps(key, sort_keys=True, default=str).encode()
    return "serve-" + hashlib.sha256(blob).hexdigest()[:32]


def _trial_id_for(serving: Dict[str, Any]) -> int:
    from determined_tpu.core._checkpoint import _STATE_ID_RE

    ckpt = str(serving.get("checkpoint", "latest"))
    m = _STATE_ID_RE.match(ckpt)
    if m:
        return int(m.group(1))
    return int(serving.get("trial_id", 0))


def build_replica(config: Dict[str, Any], session=None):
    """Config → (engine, batcher). Shared by the cluster task, the local
    CLI mode, tests, and the bench."""
    from determined_tpu.core._checkpoint import CheckpointContext
    from determined_tpu.serve.engine import (
        ServingEngine, load_checkpoint_params)
    from determined_tpu.serve.kv_cache import BlockManager
    from determined_tpu.serve.scheduler import (
        AdmissionQueue, ContinuousBatcher)
    from determined_tpu.storage import from_config

    serving = config.get("serving") or {}
    cfg = build_model(serving)
    storage = from_config(config.get("checkpoint_storage"))
    ckpt_ctx = CheckpointContext(
        session, storage, trial_id=_trial_id_for(serving), async_save=False)
    params = load_checkpoint_params(
        ckpt_ctx, str(serving.get("checkpoint", "latest")))

    # Multi-adapter replicas (docs/serving.md "Model lifecycle"): each
    # serving.adapters entry restores a head-tuned fine-tune through the
    # same verified-COMPLETED path as the base, then lives as one table
    # in the engine's adapter stack — per-request `model:` names select
    # it. Adapter checkpoints may come from other trials; each resolves
    # its own lineage scope from its checkpoint id.
    adapters = {}
    for a in serving.get("adapters") or []:
        a_ckpt = str(a["checkpoint"])
        from determined_tpu.core._checkpoint import _STATE_ID_RE

        m = _STATE_ID_RE.match(a_ckpt)
        a_ctx = CheckpointContext(
            session, storage,
            trial_id=int(m.group(1)) if m else _trial_id_for(serving),
            async_save=False)
        adapters[str(a["name"])] = load_checkpoint_params(a_ctx, a_ckpt)

    slots = int(serving.get("max_batch_size", 8))
    max_seq = int(serving.get("max_seq_len", min(cfg.n_positions, 1024)))
    block_size = int(serving.get("kv_block_size", 16))
    num_blocks = serving.get("kv_num_blocks")
    engine = ServingEngine(
        params, cfg,
        slots=slots,
        max_seq_len=max_seq,
        prefill_buckets=serving.get("prefill_buckets"),
        seed=int(serving.get("seed", 0)),
        attention_impl=str(serving.get("attention_impl", "auto")),
        kv_block_size=block_size,
        kv_num_blocks=int(num_blocks) if num_blocks else None,
        adapters=adapters or None,
    )
    # Warm AOT (docs/serving.md "Scale to zero"): scope a compile-farm
    # client to this config's serving signature so compile() deserializes
    # executables from the node-local AOT dir / master artifact store and
    # saves fresh compiles back. Opt out with serving.warm_aot: false.
    if serving.get("warm_aot", True):
        from determined_tpu.compile.runtime import FarmClient

        engine.farm = FarmClient(
            session=session, signature=serving_signature(serving))
    if engine.paged:
        # The device pool IS the budget: the manager mirrors it exactly.
        blocks = BlockManager(
            num_blocks=engine.num_blocks, block_size=engine.block_size,
            prefix_cache=bool(serving.get("prefix_cache", True)))
    else:
        blocks = BlockManager(
            num_blocks=slots * max(1, (engine.max_seq_len + block_size - 1)
                                   // block_size),
            block_size=block_size,
        )
    queue = AdmissionQueue(maxsize=int(serving.get("queue_depth", 64)))
    batcher = ContinuousBatcher(engine, queue=queue, block_manager=blocks)
    return engine, batcher


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO, format="%(name)s: %(message)s")
    argv = list(sys.argv[1:] if argv is None else argv)

    raw = os.environ.get("DET_SERVING_CONFIG")
    if raw is None and argv:
        with open(argv[0]) as f:  # local mode: config file on the cli
            raw = f.read()
    if not raw:
        print("no serving config (DET_SERVING_CONFIG or a config path)",
              file=sys.stderr)
        return 1
    config = json.loads(raw) if raw.lstrip().startswith("{") else __import__(
        "yaml").safe_load(raw)

    master = os.environ.get("DET_MASTER")
    allocation_id = os.environ.get("DET_ALLOCATION_ID")
    session = None
    if master and allocation_id:
        from determined_tpu.common.api import Session

        session = Session(master, os.environ.get("DET_SESSION_TOKEN"))

    engine, batcher = build_replica(config, session=session)

    # Per-request span tracing (docs/observability.md "Request spans"):
    # retire-time span trees batch-POST to the master's request_spans
    # store; errors/SLO breaches always traced, the rest at
    # serving.trace_sample. serving.trace_sample: 0 disables entirely.
    from determined_tpu.serve.tracing import RequestTracer

    serving_cfg = config.get("serving") or {}
    sample = float(serving_cfg.get("trace_sample", 1.0))
    tracer = None
    if sample > 0:
        tracer = RequestTracer(
            session, allocation_id or "", sample=sample,
            slo_ms=serving_cfg.get("slo_ms"))
        batcher.tracer = tracer
        tracer.start()

    batcher.start()  # compiles everything AOT before serving

    from determined_tpu.serve.http import ServingServer

    serving = config.get("serving") or {}
    server = ServingServer(batcher, port=int(serving.get("port", 0)))
    server.start()
    addr = f"http://{socket.gethostname()}:{server.port}"
    logger.info("serve replica up at %s (slots=%d, buckets=%s)",
                addr, engine.slots, engine.prefill_buckets)

    from determined_tpu.exec._util import report_proxy_address

    report_proxy_address(addr)
    if session is not None and allocation_id:
        try:
            session.post(f"/api/v1/allocations/{allocation_id}/ready")
        except Exception:
            logger.warning("ready report failed", exc_info=True)

    heartbeat = ReplicaHeartbeat(
        session, allocation_id or "", batcher,
        period_s=float(serving.get("heartbeat_period_s",
                                   HEARTBEAT_PERIOD_S)))
    heartbeat.start()

    # -- drain plumbing -------------------------------------------------
    from determined_tpu.core._preempt import PreemptContext

    preempt = PreemptContext(session, allocation_id)
    drain_requested = threading.Event()

    def _sigterm(signum, frame):
        logger.info("SIGTERM: draining")
        drain_requested.set()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)

    stats_every = float(serving.get("stats_log_period_s", 30.0))
    last_stats = time.monotonic()
    try:
        while not drain_requested.is_set():
            if preempt.should_preempt():
                logger.info(
                    "preemption signal (%s): draining",
                    preempt.preemption_reason() or "unspecified")
                break
            if stats_every and time.monotonic() - last_stats >= stats_every:
                last_stats = time.monotonic()
                logger.info("stats: %s", json.dumps(batcher.stats()))
            time.sleep(0.5)

        # Drain: stop admitting (HTTP 503), finish accepted work inside
        # the grace window, then exit cleanly so the master reschedules.
        deadline = preempt.preemption_deadline()
        budget = (max(1.0, deadline - DRAIN_SAFETY_MARGIN_S)
                  if deadline is not None else 60.0)
        t0 = time.monotonic()
        batcher.queue.drain()
        # Drain handshake: report draining=true NOW so the deployment
        # router stops dispatching here immediately instead of waiting
        # out the heartbeat period (requests it already forwarded still
        # finish — that's the zero-dropped contract below).
        heartbeat.beat()
        finished = batcher.drain(timeout=budget)
        logger.info(
            "drain %s in %.2fs (budget %.1fs): %s",
            "complete" if finished else "TIMED OUT", time.monotonic() - t0,
            budget, json.dumps(batcher.stats()))
        # Clean exit either way — a blown budget means the node is about
        # to die; rescheduling beats burning the rest of the grace.
        return 0
    finally:
        heartbeat.stop()
        server.stop()
        batcher.stop()
        if tracer is not None:
            tracer.stop()  # final flush: drained requests keep traces
        preempt.close()


if __name__ == "__main__":
    sys.exit(main())
