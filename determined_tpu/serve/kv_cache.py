"""KV-cache block manager — paged admission control + prefix caching.

The device-side KV cache is a paged block pool (`serve/model.py`
`init_paged_cache`: `[L, num_blocks + 1, block_size, H, Dh]`, the last
block being the trash block the manager never hands out). This manager
owns the pool's HOST-side truth, vLLM style:

  - **allocation**: a sequence is admitted only when enough blocks exist
    to cover its worst case (prompt + max_new_tokens); exhaustion keeps
    it queued (backpressure, never failure). Because the device layout is
    paged too (the tables this manager hands out index the real pool),
    the accounting now bounds actual HBM — not a worst-case `slots ×
    max_seq` reservation.
  - **prefix caching**: full prompt blocks are registered in a chained
    hash index (`hash(chunk_0)`, `hash(h_0, chunk_1)`, … — a hit at
    depth i implies the whole prefix matches). A new prompt reuses every
    matching block by bumping its refcount; admission charges only the
    novel suffix's blocks. Retired prompt blocks with no remaining
    sharers park in an LRU "cached" pool: still reusable by the next
    matching prompt, evicted only when a fresh allocation needs the
    space — so a fleet serving a shared system prompt pays its KV once.
  - **copy-on-write**: a sequence that must write into a block whose
    content other sequences still reference gets a private copy (the
    caller mirrors the copy on-device via `engine.copy_block`). With
    full-block-granular sharing this only happens when a prompt is a
    complete cache hit and the last token must be recomputed for its
    logits.

Thread-safe: the batcher allocates at step boundaries while the HTTP
front-end reads stats. Misuse (double admit, unknown free) raises —
an accounting bug must surface, not silently skew capacity.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence, Tuple


class KVBlockError(ValueError):
    """Inconsistent block-manager use (double free, unknown sequence)."""


class BlockManager:
    """Fixed pool of refcounted KV blocks with a prefix-reuse index."""

    def __init__(self, num_blocks: int, block_size: int,
                 prefix_cache: bool = True):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_cache = bool(prefix_cache)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._owned: Dict[str, List[int]] = {}  # seq id -> block ids
        self._refs: Dict[int, int] = {}         # block id -> refcount
        self._block_hash: Dict[int, int] = {}   # block id -> chain hash
        self._hash_block: Dict[int, int] = {}   # chain hash -> block id
        # ref==0 prompt blocks retained for reuse, LRU order (oldest first).
        self._cached: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()
        self._ever_freed: set = set()  # block ids that have cycled back
        # Lifetime counters (stats / tests).
        self.total_allocated = 0
        self.total_freed = 0
        self.total_reused = 0
        self.prefix_queries = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prompt_tokens_seen = 0
        self.cached_evictions = 0
        self.cow_copies = 0

    # -- geometry ------------------------------------------------------

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks covering `n_tokens` (ceil division; 0 tokens → 0)."""
        return (max(0, n_tokens) + self.block_size - 1) // self.block_size

    @property
    def free_blocks(self) -> int:
        """Blocks available to a new allocation: truly free + cached
        (evictable) prefix blocks nobody references."""
        with self._lock:
            return len(self._free) + len(self._cached)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def cached_blocks(self) -> int:
        """Evictable ref==0 prompt blocks retained for prefix reuse."""
        with self._lock:
            return len(self._cached)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for_tokens(n_tokens) <= self.free_blocks

    # -- internal pool ops (lock held) ---------------------------------

    def _take_locked(self) -> int:
        """Pop one block: free list first, then evict the LRU cached
        prefix block (dropping its index entry). Caller checked capacity."""
        if self._free:
            blk = self._free.pop()
        else:
            blk, h = self._cached.popitem(last=False)  # LRU
            self._hash_block.pop(h, None)
            self._block_hash.pop(blk, None)
            self.cached_evictions += 1
        if blk in self._ever_freed:
            self.total_reused += 1
        self._refs[blk] = 1
        self.total_allocated += 1
        return blk

    def _release_locked(self, blk: int, discard: bool) -> None:
        """Drop one reference; at zero the block parks (hashed prompt
        block) or returns to the free list."""
        refs = self._refs.get(blk, 0) - 1
        if refs < 0:
            raise KVBlockError(f"block {blk} over-released")
        if refs > 0:
            self._refs[blk] = refs
            return
        self._refs.pop(blk, None)
        self._ever_freed.add(blk)
        self.total_freed += 1  # counted when the block truly leaves use
        h = self._block_hash.get(blk)
        if h is not None and self.prefix_cache and not discard:
            self._cached[blk] = h
            self._cached.move_to_end(blk)
        else:
            if h is not None:
                self._hash_block.pop(h, None)
                self._block_hash.pop(blk, None)
            self._free.append(blk)

    @staticmethod
    def _chain_hashes(prompt: Sequence[int], block_size: int) -> List[int]:
        """Chained content hashes of the prompt's FULL blocks: a match at
        depth i implies blocks 0..i all match (the hash folds the
        previous hash in)."""
        hashes: List[int] = []
        h = 0
        for i in range(len(prompt) // block_size):
            chunk = tuple(int(t) for t in
                          prompt[i * block_size:(i + 1) * block_size])
            h = hash((h, chunk))
            hashes.append(h)
        return hashes

    # -- admission (paged + prefix-aware) ------------------------------

    def admit(
        self, seq_id: str, prompt: Sequence[int], total_tokens: int
    ) -> Optional[Tuple[List[int], int, List[Tuple[int, int]]]]:
        """Admit a sequence: reuse cached prefix blocks, charge only the
        rest.

        Returns `(block_table, cached_len, cow_pairs)` or None when the
        pool can't cover the charge (caller keeps the request queued):

          - `block_table`: pool block ids in logical order, covering
            `total_tokens` (prompt + every future generated token);
          - `cached_len`: prompt tokens whose K/V need NO recompute —
            always < len(prompt), so prefill has at least one query to
            produce logits from;
          - `cow_pairs`: `(src, dst)` device copies the caller must
            perform before writing (a full-prompt cache hit whose final
            block is still shared).
        """
        prompt = list(prompt)
        n_prompt = len(prompt)
        if n_prompt <= 0:
            raise KVBlockError("cannot admit an empty prompt")
        if total_tokens < n_prompt:
            raise KVBlockError("total_tokens must cover the prompt")
        need_total = self.blocks_for_tokens(total_tokens)
        with self._lock:
            if seq_id in self._owned:
                raise KVBlockError(f"sequence {seq_id!r} already owns blocks")
            matched: List[int] = []
            if self.prefix_cache:
                for h in self._chain_hashes(prompt, self.block_size):
                    blk = self._hash_block.get(h)
                    if blk is None:
                        break
                    matched.append(blk)
            cached_len = len(matched) * self.block_size
            # Prefill needs >= 1 query token for the next-token logits; a
            # full-prompt hit recomputes (and rewrites) the last token.
            cow_needed = 0
            if cached_len >= n_prompt:
                cached_len = n_prompt - 1
                last = matched[-1]
                # The recompute writes into the final matched block; a
                # private copy is only needed while others reference it
                # (a parked ref==0 block is exclusively ours once pinned).
                if self._refs.get(last, 0) > 0:
                    cow_needed = 1
            # Capacity: free + evictable-cached, EXCLUDING matched blocks
            # (they are about to be pinned, not evicted).
            need_new = need_total - len(matched) + cow_needed
            evictable = sum(1 for b in self._cached if b not in matched)
            if need_new > len(self._free) + evictable:
                return None
            # Pin the matched prefix blocks.
            for blk in matched:
                if blk in self._cached:
                    del self._cached[blk]
                self._refs[blk] = self._refs.get(blk, 0) + 1
            cow_pairs: List[Tuple[int, int]] = []
            if cow_needed:
                src = matched[-1]
                dst = self._take_locked()
                cow_pairs.append((src, dst))
                self.cow_copies += 1
                # The copy replaces the shared block in THIS table only.
                self._release_locked(src, discard=False)
                matched[-1] = dst
            table = list(matched)
            for _ in range(need_total - len(matched)):
                table.append(self._take_locked())
            self._owned[seq_id] = table
            # Counters move only on a SUCCESSFUL admission: a blocked
            # request retries every step boundary, and counting each
            # attempt would skew the hit rate.
            self.prompt_tokens_seen += n_prompt
            # Register the new full prompt blocks for future reuse (the
            # batcher prefills them before the next admission runs, so
            # registering now is safe in the single-consumer batcher).
            if self.prefix_cache:
                self.prefix_queries += 1
                hashes = self._chain_hashes(prompt, self.block_size)
                if matched:
                    self.prefix_hits += 1
                self.prefix_hit_tokens += cached_len
                for i, h in enumerate(hashes):
                    if h not in self._hash_block:
                        self._hash_block[h] = table[i]
                        self._block_hash[table[i]] = h
            return list(table), cached_len, cow_pairs

    # -- legacy allocation (no prompt content → no prefix reuse) -------

    def allocate(self, seq_id: str, n_tokens: int) -> Optional[List[int]]:
        """Reserve blocks for a sequence of up to `n_tokens` tokens.

        Returns the block ids, or None when the pool can't cover it (the
        caller keeps the request queued — backpressure, not failure).
        """
        need = self.blocks_for_tokens(n_tokens)
        with self._lock:
            if seq_id in self._owned:
                raise KVBlockError(f"sequence {seq_id!r} already owns blocks")
            if need > len(self._free) + len(self._cached):
                return None
            blocks = [self._take_locked() for _ in range(need)]
            self._owned[seq_id] = blocks
            return list(blocks)

    def extend(self, seq_id: str, n_tokens: int) -> bool:
        """Grow a sequence's reservation to cover `n_tokens` total. True on
        success; False when the pool is exhausted (caller must retire or
        reject)."""
        with self._lock:
            owned = self._owned.get(seq_id)
            if owned is None:
                raise KVBlockError(f"sequence {seq_id!r} owns no blocks")
            need = self.blocks_for_tokens(n_tokens) - len(owned)
            if need <= 0:
                return True
            if need > len(self._free) + len(self._cached):
                return False
            owned.extend(self._take_locked() for _ in range(need))
            return True

    def free(self, seq_id: str, discard: bool = False) -> int:
        """Release a retired sequence's references; returns the block
        count released. Shared blocks stay resident for their other
        owners; sole-owned prompt blocks park in the prefix cache
        (`discard=True` — e.g. a failed prefill whose K/V never got
        written — sends them straight back to the free list instead).
        Double-free / unknown ids raise."""
        with self._lock:
            blocks = self._owned.pop(seq_id, None)
            if blocks is None:
                raise KVBlockError(f"sequence {seq_id!r} owns no blocks")
            for blk in blocks:
                self._release_locked(blk, discard)
            return len(blocks)

    def owned(self, seq_id: str) -> List[int]:
        with self._lock:
            return list(self._owned.get(seq_id, ()))

    def ref_count(self, block_id: int) -> int:
        with self._lock:
            return self._refs.get(block_id, 0)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            free = len(self._free) + len(self._cached)
            hit_rate = (self.prefix_hit_tokens / self.prompt_tokens_seen
                        if self.prompt_tokens_seen else 0.0)
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "free_blocks": free,
                "used_blocks": self.num_blocks - free,
                "cached_blocks": len(self._cached),
                "total_allocated": self.total_allocated,
                "total_freed": self.total_freed,
                "total_reused": self.total_reused,
                "prefix_cache": self.prefix_cache,
                "prefix_queries": self.prefix_queries,
                "prefix_hits": self.prefix_hits,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prompt_tokens_seen": self.prompt_tokens_seen,
                "prefix_cache_hit_rate": round(hit_rate, 4),
                "cached_evictions": self.cached_evictions,
                "cow_copies": self.cow_copies,
            }
