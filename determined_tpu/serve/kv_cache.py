"""KV-cache block manager — admission-control accounting for `det serve`.

The device-side KV cache is a slot-dense tensor (one lane per concurrent
sequence, engine.py); HBM *budgeting* over it is block-granular, vLLM
style: the cache's token capacity is carved into fixed-size blocks and a
sequence may only be admitted when enough free blocks exist to cover its
worst case (prompt + max_new_tokens). Blocks return to the free pool the
moment a sequence retires — without draining the batch — so the
continuous batcher can immediately admit the next queued request.

Host-side by design: the block map never reaches the device (the decode
step indexes the dense cache by slot), so the accounting costs nothing on
the hot path. A paged device layout (block-table gather in the attention
kernel) can later slot in behind this same interface.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class KVBlockError(ValueError):
    """Inconsistent block-manager use (double free, unknown sequence)."""


class BlockManager:
    """Fixed pool of KV blocks; allocate on admit, free on retire.

    Thread-safe: the batcher allocates at step boundaries while the HTTP
    front-end reads `free_blocks` for stats.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._owned: Dict[str, List[int]] = {}  # seq id -> block ids
        self._ever_freed: set = set()  # block ids that have cycled back
        # Lifetime counters (stats / tests): every block ever handed out
        # and returned. reused grows once freed blocks start cycling back.
        self.total_allocated = 0
        self.total_freed = 0
        self.total_reused = 0

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks covering `n_tokens` (ceil division; 0 tokens → 0)."""
        return (max(0, n_tokens) + self.block_size - 1) // self.block_size

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for_tokens(n_tokens) <= self.free_blocks

    def allocate(self, seq_id: str, n_tokens: int) -> Optional[List[int]]:
        """Reserve blocks for a sequence of up to `n_tokens` tokens.

        Returns the block ids, or None when the pool can't cover it (the
        caller keeps the request queued — backpressure, not failure).
        """
        need = self.blocks_for_tokens(n_tokens)
        with self._lock:
            if seq_id in self._owned:
                raise KVBlockError(f"sequence {seq_id!r} already owns blocks")
            if need > len(self._free):
                return None
            blocks = [self._free.pop() for _ in range(need)]
            self._owned[seq_id] = blocks
            self.total_allocated += need
            self.total_reused += sum(1 for b in blocks if b in self._ever_freed)
            return list(blocks)

    def extend(self, seq_id: str, n_tokens: int) -> bool:
        """Grow a sequence's reservation to cover `n_tokens` total. True on
        success; False when the pool is exhausted (caller must retire or
        reject)."""
        with self._lock:
            owned = self._owned.get(seq_id)
            if owned is None:
                raise KVBlockError(f"sequence {seq_id!r} owns no blocks")
            need = self.blocks_for_tokens(n_tokens) - len(owned)
            if need <= 0:
                return True
            if need > len(self._free):
                return False
            grown = [self._free.pop() for _ in range(need)]
            owned.extend(grown)
            self.total_allocated += need
            self.total_reused += sum(1 for b in grown if b in self._ever_freed)
            return True

    def free(self, seq_id: str) -> int:
        """Return a retired sequence's blocks to the pool; returns the
        count. Double-free / unknown ids raise — an accounting bug must
        surface, not silently skew capacity."""
        with self._lock:
            blocks = self._owned.pop(seq_id, None)
            if blocks is None:
                raise KVBlockError(f"sequence {seq_id!r} owns no blocks")
            self._free.extend(reversed(blocks))
            self._ever_freed.update(blocks)
            self.total_freed += len(blocks)
            return len(blocks)

    def owned(self, seq_id: str) -> List[int]:
        with self._lock:
            return list(self._owned.get(seq_id, ()))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "free_blocks": len(self._free),
                "used_blocks": self.num_blocks - len(self._free),
                "total_allocated": self.total_allocated,
                "total_freed": self.total_freed,
                "total_reused": self.total_reused,
            }
