"""Run-time side of the compile farm (docs/compile-farm.md).

Two artifact kinds live under one signature in the master's
content-addressed blob store:

- ``aot-<executable>-<runtime_tag>.bin`` — a pickled
  `jax.experimental.serialize_executable` payload. Loading one skips
  trace + lowering + compile entirely: the first step of a warm trial
  costs a deserialize (tens of ms) instead of seconds. This is what takes
  `cached_median_compile_s` to ~0.
- everything else — files from the persistent XLA compilation cache dir
  (`DET_XLA_CACHE_DIR`), uploaded verbatim under XLA's own content-hash
  names. Pre-warming a node with them is always SAFE regardless of
  signature precision: XLA only ever hits a cache entry whose key (full
  HLO + compile options + versions) matches exactly; a stray file is
  wasted bytes, never a wrong executable.

`FarmClient` resolves artifacts local-first (the agent pre-warms
`DET_COMPILE_AOT_DIR/<signature>/` before the container starts, overlapped
with image setup) and falls back to `GET /api/v1/compile_cache/{sig}`.
Fresh compiles upload their serialized executables + new cache files in a
background thread — never on the step path. Every failure here degrades to
the plain jit path: the farm is an accelerator, not a dependency.
"""

from __future__ import annotations

import base64
import logging
import os
import pickle
import threading
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from determined_tpu.compile.signature import runtime_tag

logger = logging.getLogger("determined_tpu.compile")

AOT_PREFIX = "aot-"


def aot_artifact_name(executable: str) -> str:
    return f"{AOT_PREFIX}{executable}-{runtime_tag()}.bin"


def serialize_compiled(compiled: Any) -> bytes:
    """Pickle a jax Compiled (payload + in/out treedefs) for the store."""
    from jax.experimental import serialize_executable as se

    return pickle.dumps(se.serialize(compiled))


def load_compiled(data: bytes) -> Callable:
    """Inverse of serialize_compiled. Raises on any incompatibility
    (platform, jax version, aval mismatch surfaces at first call) — callers
    catch and fall back to jit."""
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = pickle.loads(data)
    return se.deserialize_and_load(payload, in_tree, out_tree)


def snapshot_cache_dir(cache_dir: Optional[str]) -> Set[str]:
    if not cache_dir or not os.path.isdir(cache_dir):
        return set()
    try:
        return set(os.listdir(cache_dir))
    except OSError:
        return set()


def new_cache_files(cache_dir: Optional[str],
                    before: Set[str]) -> Dict[str, bytes]:
    """Files added to the persistent XLA cache since `before` — exactly the
    entries this process compiled fresh."""
    out: Dict[str, bytes] = {}
    for name in snapshot_cache_dir(cache_dir) - before:
        try:
            with open(os.path.join(cache_dir, name), "rb") as f:
                out[name] = f.read()
        except OSError:
            continue
    return out


class FarmClient:
    """Fetch/upload compile artifacts for ONE signature.

    `signature` comes from DET_COMPILE_SIGNATURE (master-minted) in managed
    mode; local/bench runs pass their own. A falsy signature disables the
    client (every method becomes a cheap no-op)."""

    def __init__(
        self,
        session: Any = None,
        signature: Optional[str] = None,
        aot_dir: Optional[str] = None,
        xla_cache_dir: Optional[str] = None,
    ):
        self.signature = signature if signature is not None else \
            os.environ.get("DET_COMPILE_SIGNATURE", "")
        self._session = session
        self.aot_dir = aot_dir if aot_dir is not None else \
            os.environ.get("DET_COMPILE_AOT_DIR", "")
        self.xla_cache_dir = xla_cache_dir if xla_cache_dir is not None else \
            os.environ.get("DET_XLA_CACHE_DIR", "")
        self._cache_before = snapshot_cache_dir(self.xla_cache_dir)
        self._threads: List[threading.Thread] = []

    @property
    def enabled(self) -> bool:
        return bool(self.signature)

    # -- fetch ---------------------------------------------------------

    def _local_path(self, name: str) -> Optional[str]:
        if not self.aot_dir or not self.signature:
            return None
        path = os.path.join(self.aot_dir, self.signature, name)
        return path if os.path.isfile(path) else None

    def fetch(self, name: str) -> Optional[bytes]:
        """Artifact bytes: agent-prewarmed local dir first, then master."""
        if not self.enabled:
            return None
        path = self._local_path(name)
        if path is not None:
            try:
                with open(path, "rb") as f:
                    return f.read()
            except OSError:
                pass
        if self._session is None:
            return None
        try:
            resp = self._session.get(
                f"/api/v1/compile_cache/{self.signature}",
                params={"name": name})
        except Exception:
            logger.debug("compile_cache fetch failed", exc_info=True)
            return None
        for f in (resp or {}).get("files", []):
            if f.get("name") == name and f.get("b64"):
                return base64.b64decode(f["b64"])
        return None

    def load_executable(self, executable: str) -> Optional[Callable]:
        """Deserialize the signature's AOT artifact for `executable`
        (train_step/eval_step), or None. Never raises."""
        data = self.fetch(aot_artifact_name(executable))
        if data is None:
            return None
        try:
            return load_compiled(data)
        except Exception:
            logger.warning(
                "AOT artifact for %s/%s failed to load; falling back to jit",
                self.signature[:12], executable, exc_info=True)
            return None

    # -- upload --------------------------------------------------------

    def upload(self, files: Dict[str, bytes],
               compile_ms: Optional[float] = None,
               fingerprint: str = "") -> bool:
        if not self.enabled or self._session is None or not files:
            return False
        body: Dict[str, Any] = {
            "files": {n: base64.b64encode(b).decode()
                      for n, b in files.items()},
        }
        if compile_ms is not None:
            body["compile_ms"] = float(compile_ms)
        if fingerprint:
            body["fingerprint"] = fingerprint
        try:
            self._session.post(
                f"/api/v1/compile_cache/{self.signature}", body=body,
                idempotent=True)
            return True
        except Exception:
            # Best-effort by contract, like span flushes: a dead artifact
            # sink must never hurt the trial.
            logger.warning("compile artifact upload failed", exc_info=True)
            return False

    def upload_async(self, files: Dict[str, bytes],
                     compile_ms: Optional[float] = None) -> None:
        t = threading.Thread(
            target=self.upload, args=(files,),
            kwargs={"compile_ms": compile_ms},
            name="det-compile-upload", daemon=True)
        t.start()
        self._threads.append(t)

    def save_local(self, files: Dict[str, bytes]) -> bool:
        """Write artifacts into the node-local AOT dir (the same place the
        agent pre-warms into), so the NEXT process on this node warm-loads
        them even without a master round-trip — serving replicas use this
        for scale-from-zero cold starts. Best-effort like upload."""
        if not self.signature or not self.aot_dir or not files:
            return False
        try:
            d = os.path.join(self.aot_dir, self.signature)
            os.makedirs(d, exist_ok=True)
            for name, data in files.items():
                tmp = os.path.join(d, name + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, os.path.join(d, name))
            return True
        except OSError:
            logger.debug("local AOT save failed", exc_info=True)
            return False

    def collect_new_cache_files(self) -> Dict[str, bytes]:
        return new_cache_files(self.xla_cache_dir, self._cache_before)

    def export_and_upload_async(self, jit_fn: Callable, args: Tuple,
                                executable: str,
                                compile_ms: Optional[float] = None) -> None:
        """After a fresh in-trial compile: re-lower the step abstractly in
        the background, serialize the (persistent-cache-hit) compiled
        executable and upload it with the new XLA cache files. Off the step
        path; abstract args only (no buffers pinned)."""
        if not self.enabled or self._session is None:
            return
        import jax

        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") and hasattr(x, "dtype") else x, args)

        def work():
            files: Dict[str, bytes] = {}
            try:
                compiled = jit_fn.lower(*abstract).compile()
                files[aot_artifact_name(executable)] = \
                    serialize_compiled(compiled)
            except Exception:
                logger.debug("AOT export failed; uploading cache files only",
                             exc_info=True)
            files.update(self.collect_new_cache_files())
            if files:
                self.upload(files, compile_ms=compile_ms)

        t = threading.Thread(target=work, name="det-compile-export",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def wait(self, timeout: float = 30.0) -> None:
        """Join outstanding uploads (tests + clean trial exit)."""
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]
