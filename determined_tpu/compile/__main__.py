"""`python -m determined_tpu.compile` — the farm worker entrypoint.

XLA_FLAGS must be set BEFORE jax is imported anywhere: a CPU compile host
needs as many virtual devices as the job's slot count for the mesh to
resolve (TPU hosts use their real chips — the worker only runs on idle
agents, so the chips are free by construction).
"""

import os
import sys


def _force_cpu_devices() -> None:
    slots = int(os.environ.get("DET_COMPILE_SLOTS", "1"))
    if slots <= 1:
        return
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms and "cpu" not in platforms:
        return  # real accelerators: use the host's chips
    flag = f"--xla_force_host_platform_device_count={slots}"
    existing = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in existing:
        os.environ["XLA_FLAGS"] = (existing + " " + flag).strip()


_force_cpu_devices()

from determined_tpu.compile.worker import main  # noqa: E402

sys.exit(main())
