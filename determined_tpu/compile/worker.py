"""Compile-farm worker: one background AOT compile job (docs/compile-farm.md).

Dispatched by the master to an IDLE agent (action type "compile"), so queued
time becomes compile time instead of allocation time. The worker:

  1. downloads the experiment's model-def context and instantiates the trial
     (same loader as `det preflight`),
  2. traces the trial's step fingerprint; if an already-DONE job has the
     same fingerprint it LINKS that job's artifacts to this signature and
     exits without compiling (executable sharing, fingerprint-verified —
     this is how an `inject_hyperparams` lr sweep ends up with one
     executable for N signatures),
  3. otherwise AOT-compiles the jitted train step (and eval step when the
     trial has one) under the declared mesh via `jit().lower().compile()`,
     serializes the executables, and uploads them plus the new persistent
     XLA-cache entries to `POST /api/v1/compile_cache/{signature}`.

The worker also runs with `DET_XLA_CACHE_DIR` pointing at the agent's
shared cache dir, so the compiling node itself is warm before any artifact
round-trips.

Environment contract (set by the master's dispatch, master_compile.cc):
  DET_MASTER, DET_SESSION_TOKEN, DET_COMPILE_SIGNATURE,
  DET_COMPILE_HPARAMS (json), DET_COMPILE_SLOTS, DET_EXPERIMENT_ID,
  DET_EXPERIMENT_CONFIG (json), DET_XLA_CACHE_DIR.
"""

from __future__ import annotations

import base64
import io
import json
import logging
import os
import sys
import tarfile
import tempfile
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("determined_tpu.compile.worker")


def _extract_model_def(b64: str, workdir: str) -> None:
    raw = base64.b64decode(b64)
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
        for member in tar.getmembers():
            target = os.path.realpath(os.path.join(workdir, member.name))
            if not target.startswith(os.path.realpath(workdir)):
                raise RuntimeError(
                    f"unsafe path in context tar: {member.name}")
        tar.extractall(workdir)


def _load_trial(workdir: str, hparams: Dict[str, Any], slots: int):
    from determined_tpu.analysis._preflight import (
        find_trial_classes,
        load_trial,
    )

    classes = find_trial_classes(workdir)
    if not classes:
        raise RuntimeError("no JaxTrial subclass in the model definition; "
                           "only Trainer-based trials are farm-compilable")
    path, class_name = classes[0]
    return load_trial(path, class_name, hparams, slots)


def run_job(session, signature: str, hparams: Dict[str, Any], slots: int,
            experiment_id: int, config: Dict[str, Any],
            workdir: Optional[str] = None) -> Dict[str, Any]:
    """Execute one compile job; returns a summary dict. Raises on failure
    (the caller reports FAILED)."""
    import jax

    from determined_tpu import _jax_compat
    from determined_tpu.compile.bucketing import CompileConfig
    from determined_tpu.compile.runtime import (
        FarmClient,
        aot_artifact_name,
        serialize_compiled,
    )
    from determined_tpu.compile.signature import step_fingerprint
    from determined_tpu.core._context import _enable_compilation_cache
    from determined_tpu.parallel.mesh import create_mesh
    from determined_tpu.train.state import abstract_train_state
    from determined_tpu.train.step import make_eval_step, make_train_step

    _jax_compat.install()
    _enable_compilation_cache()
    t_start = time.time()

    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="det-compile-")
        resp = session.get(f"/api/v1/experiments/{experiment_id}/model_def")
        b64 = (resp or {}).get("b64_tgz") or ""
        if not b64:
            raise RuntimeError(
                f"experiment {experiment_id} has no model definition")
        _extract_model_def(b64, workdir)

    trial = _load_trial(workdir, hparams, slots)
    cfg = CompileConfig.resolve(trial, config)
    client = FarmClient(session, signature)

    # Fingerprint first: a trace is ~100x cheaper than a compile, and an
    # identical program may already be compiled under another signature.
    fingerprint, detail = step_fingerprint(trial, slots, cfg=cfg)
    try:
        done = session.get("/api/v1/compile_jobs",
                           params={"state": "DONE",
                                   "fingerprint": fingerprint})
    except Exception:
        done = {}
    for job in (done or {}).get("jobs", []):
        other = job.get("signature", "")
        if other and other != signature:
            session.post(f"/api/v1/compile_jobs/{signature}/link",
                         body={"from": other, "fingerprint": fingerprint},
                         idempotent=True)
            return {"signature": signature, "linked_from": other,
                    "fingerprint": fingerprint,
                    "wall_s": round(time.time() - t_start, 2)}

    devices = jax.devices()
    if slots > len(devices):
        raise RuntimeError(
            f"compile job needs {slots} devices, worker host has "
            f"{len(devices)} (set --xla_force_host_platform_device_count "
            "via the launcher on CPU hosts)")
    mesh = create_mesh(trial.mesh_config().resolve(slots), devices[:slots])
    tx = trial.optimizer()
    axes = trial.param_logical_axes()
    rules = trial.sharding_rules()
    state_sds = abstract_train_state(
        trial.init_params, tx, mesh, axes, rules, extra=trial.init_extra())

    from determined_tpu.compile.signature import _abstract_batch

    import numpy as np

    batch_sds = _abstract_batch(trial, None, cfg)
    rng_sds = jax.ShapeDtypeStruct((2,), np.uint32)

    files: Dict[str, bytes] = {}
    t0 = time.time()
    with jax.sharding.set_mesh(mesh):
        train_jit = make_train_step(
            trial.loss, tx, mesh=mesh, rules=rules,
            donate_state=trial.donate_state, stateful=trial.stateful)
        compiled = train_jit.lower(state_sds, batch_sds, rng_sds).compile()
        files[aot_artifact_name("train_step")] = serialize_compiled(compiled)
        # Eval step: best effort — validation shapes may be undrawable
        # without real data; the trial's jit path covers it either way.
        try:
            from determined_tpu.train.trial import JaxTrial

            if type(trial).evaluate is not JaxTrial.evaluate:
                val_batch = next(iter(trial.build_validation_data()), None)
                if val_batch is not None:
                    vb_sds = _abstract_batch(trial, val_batch, cfg)
                    eval_jit = make_eval_step(
                        trial.evaluate, mesh=mesh, rules=rules,
                        stateful=trial.stateful)
                    files[aot_artifact_name("eval_step")] = \
                        serialize_compiled(
                            eval_jit.lower(state_sds, vb_sds).compile())
        except Exception:
            logger.debug("eval step AOT skipped", exc_info=True)
    compile_ms = (time.time() - t0) * 1000.0

    files.update(client.collect_new_cache_files())
    client.upload(files, compile_ms=compile_ms, fingerprint=fingerprint)
    session.post(f"/api/v1/compile_jobs/{signature}",
                 body={"state": "DONE", "fingerprint": fingerprint,
                       "compile_ms": compile_ms},
                 idempotent=True)
    return {"signature": signature, "fingerprint": fingerprint,
            "compile_ms": round(compile_ms, 1), "artifacts": len(files),
            "bytes": sum(len(b) for b in files.values()),
            "wall_s": round(time.time() - t_start, 2)}


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    from determined_tpu.common.api import Session

    master = os.environ.get("DET_MASTER", "")
    token = os.environ.get("DET_SESSION_TOKEN", "")
    signature = os.environ.get("DET_COMPILE_SIGNATURE", "")
    if not master or not signature:
        print("compile worker: DET_MASTER and DET_COMPILE_SIGNATURE required",
              file=sys.stderr)
        return 2
    hparams = json.loads(os.environ.get("DET_COMPILE_HPARAMS", "{}"))
    slots = int(os.environ.get("DET_COMPILE_SLOTS", "1"))
    experiment_id = int(os.environ.get("DET_EXPERIMENT_ID", "0"))
    config = json.loads(os.environ.get("DET_EXPERIMENT_CONFIG", "{}"))
    session = Session(master, token)
    try:
        summary = run_job(session, signature, hparams, slots, experiment_id,
                          config)
    except Exception as e:
        logger.exception("compile job %s failed", signature[:12])
        try:
            session.post(f"/api/v1/compile_jobs/{signature}",
                         body={"state": "FAILED",
                               "error": f"{type(e).__name__}: {e}"},
                         idempotent=True)
        except Exception:
            pass
        return 1
    print(json.dumps(summary))
    return 0
