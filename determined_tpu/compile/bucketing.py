"""Shape canonicalization: batch-size bucketing (docs/compile-farm.md).

An XLA executable is keyed by exact input shapes, so an hparam sweep that
samples `global_batch_size` raw compiles one executable per sampled value —
the recompile explosion DTL205 warns about. Bucketing rounds the batch
dimension up to a bucket boundary (powers of two by default) *consistently
at trace time and run time*: the compile farm signs and precompiles the
bucketed shape, and the Trainer pads every loader batch to the same bucket,
so all batch sizes inside a bucket share one executable.

Padding semantics: pad rows are wrap-around repeats of real rows (never
zeros — zero rows can NaN a loss and would silently skew metrics more than
duplicates do). The loss then averages over `bucket` rows instead of `b`,
i.e. rows `0..(bucket-b)` carry double weight — equivalent to a slightly
re-weighted batch, deterministic per config. Bucketing is therefore OFF by
default and opt-in via `compile: {bucket_batch_sizes: true}`; runs of the
SAME config are always bit-identical to each other (warm or cold cache)
because both apply the identical padding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np

# DTL205's default ceiling: a sweep implying more distinct executables than
# this without bucketing gets flagged (docs/preflight.md).
DEFAULT_MAX_EXECUTABLES = 8


def bucket_size(n: int, buckets: Optional[List[int]] = None) -> int:
    """Smallest bucket boundary >= n.

    Default buckets are powers of two. With an explicit bucket list, sizes
    above the largest bucket stay unbucketed (exact) — better an extra
    executable than silently padding a huge batch to something huger.
    """
    if n <= 0:
        return n
    if buckets:
        for b in sorted(buckets):
            if b >= n:
                return int(b)
        return n
    p = 1
    while p < n:
        p <<= 1
    return p


@dataclasses.dataclass
class CompileConfig:
    """Resolved `compile:` expconf block (defaults match apply_defaults)."""

    enabled: bool = True  # participate in the farm (fetch/upload artifacts)
    background: bool = False  # master precompiles while trials queue
    bucket_batch_sizes: bool = False
    buckets: Optional[List[int]] = None  # None = powers of two
    max_executables: int = DEFAULT_MAX_EXECUTABLES  # DTL205 threshold
    upload: bool = True  # fresh compiles upload serialized executables

    @classmethod
    def from_block(cls, block: Any) -> "CompileConfig":
        if isinstance(block, bool):
            return cls(enabled=block)
        if not isinstance(block, dict):
            return cls()
        return cls(
            enabled=bool(block.get("enabled", True)),
            background=bool(block.get("background", False)),
            bucket_batch_sizes=bool(block.get("bucket_batch_sizes", False)),
            buckets=[int(b) for b in block["buckets"]]
            if block.get("buckets") else None,
            max_executables=int(
                block.get("max_executables", DEFAULT_MAX_EXECUTABLES)),
            upload=bool(block.get("upload", True)),
        )

    @classmethod
    def resolve(cls, trial: Any = None,
                expconf: Optional[Dict[str, Any]] = None) -> "CompileConfig":
        """Trial attribute `compile` wins over the experiment config block
        (the same precedence as `prefetch`, docs/trial-api.md)."""
        attr = getattr(trial, "compile", None) if trial is not None else None
        if attr is not None:
            return cls.from_block(attr)
        if expconf is not None and expconf.get("compile") is not None:
            return cls.from_block(expconf.get("compile"))
        return cls()


def _leading_batch_dim(batch: Any) -> Optional[int]:
    """The global batch size: leading dim shared by the batch leaves."""
    import jax

    for leaf in jax.tree_util.tree_leaves(batch):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1:
            return int(shape[0])
    return None


def pad_batch(batch: Any, target: int) -> Any:
    """Pad every leaf whose leading dim equals the batch size up to `target`
    rows by wrapping (repeating rows from the front). Host-side numpy — runs
    before the async input pipeline's device transfer."""
    import jax

    b = _leading_batch_dim(batch)
    if b is None or b >= target:
        return batch

    def one(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) < 1 or shape[0] != b:
            return leaf
        arr = np.asarray(leaf)
        reps = (target + b - 1) // b
        return np.concatenate([arr] * reps, axis=0)[:target]

    return jax.tree_util.tree_map(one, batch)


def bucketed_batch(batch: Any, cfg: CompileConfig) -> Any:
    """Apply run-time bucketing to one host batch (no-op when disabled)."""
    if not cfg.bucket_batch_sizes:
        return batch
    b = _leading_batch_dim(batch)
    if b is None:
        return batch
    return pad_batch(batch, bucket_size(b, cfg.buckets))


def bucketed_iter(it: Iterable[Any], cfg: CompileConfig) -> Iterator[Any]:
    """Wrap a host-batch iterator with run-time bucketing. The wrapper is
    installed UPSTREAM of the DevicePrefetcher so padded batches are what
    get sharded and transferred (shapes seen by the jitted step match the
    signed bucketed shapes exactly)."""
    for batch in it:
        yield bucketed_batch(batch, cfg)
