"""Compile farm: ahead-of-time executable signatures, artifacts and cache
warming (docs/compile-farm.md).

The pieces:
  - `signature` — config signatures (the farm's queue/store key, mirrored
    by the native master) and trace-based step fingerprints (the precise
    program identity that gates executable sharing);
  - `bucketing` — batch-size shape canonicalization, applied consistently
    at trace time and run time;
  - `runtime` — serialize/deserialize compiled executables, the artifact
    FarmClient the Trainer uses to skip trace+compile on warm trials;
  - `worker` — the agent-dispatched background compile job.
"""

from determined_tpu.compile.bucketing import (  # noqa: F401
    CompileConfig,
    bucket_size,
    bucketed_batch,
    bucketed_iter,
    pad_batch,
)
from determined_tpu.compile.runtime import (  # noqa: F401
    FarmClient,
    aot_artifact_name,
    load_compiled,
    serialize_compiled,
)
from determined_tpu.compile.signature import (  # noqa: F401
    config_signature,
    runtime_tag,
    step_fingerprint,
)
