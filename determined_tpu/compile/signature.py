"""Executable signatures (docs/compile-farm.md).

Two keys, two precision levels, one safety story:

- **config signature** — computed WITHOUT tracing, from (entrypoint,
  model-def hash, slots, the full hparam set with `global_batch_size`
  bucketed). The native master computes the same key at trial creation
  (native/master/master_compile.cc) and propagates it to containers as
  `DET_COMPILE_SIGNATURE`; it is the compile-job queue key and the
  artifact-store address. Because it hashes EVERY hparam value, two trials
  share a config signature only when their configs are interchangeable —
  there is no lossy "shape-affecting" guessing on this path.

- **step fingerprint** — the precise program identity: a hash over the
  canonicalized jaxpr of the *actual* train step (constants included, so a
  baked-in learning rate changes it), mesh shape, batch shapes/dtypes
  (bucketed), donation and jax/jaxlib/backend versions. Costs one abstract
  trace (~100ms-1s, no compile). The compile WORKER uses it to share
  executables across config signatures: before compiling job B it traces
  B's fingerprint and, when it equals an already-compiled job A's
  (`optax.inject_hyperparams` makes an lr sweep hparam-invariant — the
  platform idiom, see tests/fixtures/platform/train_jit.py), links A's
  artifacts to B instead of recompiling. Sharing is therefore always
  fingerprint-verified; a config-signature collision can never hand a trial
  an executable compiled from a different program.

Serialized executables are platform/version-specific on top of all that:
artifact filenames embed `runtime_tag()` so a CPU-compiled artifact can
never be offered to a TPU trial of the same config.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

from determined_tpu.compile.bucketing import CompileConfig, bucket_size

SIGNATURE_VERSION = "det-compile-v1"


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


def runtime_tag() -> str:
    """Short tag identifying the compile platform: a serialized executable
    only loads on the exact jax/jaxlib/backend/device-kind that built it."""
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "?")
    except Exception:
        jaxlib_v = "?"
    try:
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", platform)
    except Exception:
        platform, kind = "unknown", "unknown"
    return _sha("|".join(
        [jax.__version__, jaxlib_v, platform, str(kind)]))[:12]


def canonical_hparams(hparams: Dict[str, Any],
                      cfg: Optional[CompileConfig] = None) -> str:
    """Sorted `k=<json>` rendering of the hparam dict, with
    global_batch_size replaced by its bucket when bucketing is on. The
    native master builds the identical string (master_compile.cc) — keep
    the two in lockstep."""
    cfg = cfg or CompileConfig()
    parts = []
    for k in sorted(hparams or {}):
        v = hparams[k]
        if k == "global_batch_size" and cfg.bucket_batch_sizes and \
                isinstance(v, int) and not isinstance(v, bool):
            v = bucket_size(v, cfg.buckets)
        parts.append(f"{k}={json.dumps(v, sort_keys=True)}")
    return ";".join(parts)


def config_signature(
    hparams: Dict[str, Any],
    entrypoint: Any = "",
    model_def_hash: str = "",
    slots: int = 1,
    cfg: Optional[CompileConfig] = None,
) -> str:
    """The compile-farm grouping key for one trial (mirrors the native
    master's compile_signature_locked)."""
    ep = entrypoint if isinstance(entrypoint, str) else json.dumps(entrypoint)
    return _sha("|".join([
        SIGNATURE_VERSION, ep, model_def_hash or "", str(int(slots)),
        canonical_hparams(hparams, cfg),
    ]))


def _abstract_state(trial: Any):
    """ShapeDtypeStruct TrainState for the trial (no buffers, no compile)."""
    import jax

    from determined_tpu.train.state import TrainState

    tx = trial.optimizer()

    def init_state(r):
        params = trial.init_params(r)
        return TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            opt_state=tx.init(params),
            extra=trial.init_extra(),
        )

    return tx, jax.eval_shape(
        init_state, jax.ShapeDtypeStruct((2,), np.uint32))


def _abstract_batch(trial: Any, batch: Any,
                    cfg: Optional[CompileConfig] = None) -> Any:
    """One abstract global batch, bucketed exactly like run time."""
    import jax

    from determined_tpu.compile.bucketing import bucketed_batch

    if batch is None:
        batch = next(iter(trial.build_training_data()))
    if cfg is not None:
        batch = bucketed_batch(batch, cfg)

    def one(v):
        arr = np.asarray(v) if not hasattr(v, "shape") else v
        return jax.ShapeDtypeStruct(
            np.shape(arr), getattr(arr, "dtype", np.dtype(np.float32)))

    return jax.tree_util.tree_map(one, batch)


def _const_digest(consts) -> str:
    """Hash the VALUES closed over by the jaxpr: a learning rate baked into
    the optimizer update is invisible in the jaxpr text but changes the
    compiled program — it must change the fingerprint too."""
    h = hashlib.sha256()
    for c in consts:
        try:
            arr = np.asarray(c)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        except Exception:
            h.update(repr(c).encode())
    return h.hexdigest()


def step_fingerprint(
    trial: Any,
    n_devices: int,
    batch: Any = None,
    cfg: Optional[CompileConfig] = None,
) -> Tuple[str, Dict[str, Any]]:
    """(fingerprint hex, detail) for the trial's jitted train step.

    One abstract trace (jax.make_jaxpr), no devices touched, no compile —
    the same cost class as the preflight abstract engine. Deterministic
    across processes (tests/test_compile_farm.py asserts it): jaxpr
    variable naming is generated in traversal order and the const digest
    covers closed-over values.
    """
    import jax

    from determined_tpu.parallel.mesh import AXIS_ORDER
    from determined_tpu.train.step import make_train_step

    cfg = cfg or CompileConfig.resolve(trial)
    mesh_cfg = trial.mesh_config().resolve(n_devices)
    sizes = dict(zip(AXIS_ORDER, mesh_cfg.sizes()))
    tx, state_sds = _abstract_state(trial)
    batch_sds = _abstract_batch(trial, batch, cfg)
    rng_sds = jax.ShapeDtypeStruct((2,), np.uint32)

    # mesh=None: sharding constraints only restate the mesh shape, which is
    # hashed separately below — and tracing without a mesh works in any
    # process regardless of how many local devices it has.
    step = make_train_step(
        trial.loss, tx, mesh=None, rules=trial.sharding_rules(),
        donate_state=trial.donate_state, stateful=trial.stateful)
    fn = getattr(step, "__wrapped__", step)
    closed = jax.make_jaxpr(fn)(state_sds, batch_sds, rng_sds)

    batch_leaves = [
        (tuple(int(d) for d in leaf.shape), str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(batch_sds)
    ]
    param_dtypes = sorted({
        str(leaf.dtype)
        for leaf in jax.tree_util.tree_leaves(state_sds.params)
    })
    detail = {
        "jaxpr": _sha(str(closed.jaxpr)),
        "consts": _const_digest(closed.consts),
        "mesh": {a: int(s) for a, s in sizes.items() if s > 1},
        "n_devices": int(n_devices),
        "batch": batch_leaves,
        "param_dtypes": param_dtypes,
        "donate_state": bool(trial.donate_state),
        "stateful": bool(trial.stateful),
        "runtime_tag": runtime_tag(),
    }
    return _sha(json.dumps(detail, sort_keys=True)), detail
