/* determined-tpu WebUI — dependency-free SPA over the master REST API.
   Pages: experiments list/detail (metric charts, HP search table +
   hparam-vs-metric viz), trial detail (live log viewer with follow),
   workspaces/projects, model registry, cluster, job queue.
   Live updates ride the /api/v1/stream long-poll (reference
   internal/stream/ websocket publisher).
   Charting follows the dataviz method: fixed-order categorical slots,
   2px lines, recessive grid, crosshair+tooltip hover, legend for >=2
   series + direct labels, table view toggle. */

"use strict";

const view = document.getElementById("view");

// Generation counter: bumped on every route change so in-flight stream
// long-polls and log follows from the previous page stop re-rendering.
let gen = 0;

// ---------------------------------------------------------------- api

function token() { return localStorage.getItem("det_token") || ""; }

async function api(method, path, body) {
  const resp = await fetch(path, {
    method,
    headers: {
      "Content-Type": "application/json",
      ...(token() ? { Authorization: `Bearer ${token()}` } : {}),
    },
    body: body === undefined ? undefined : JSON.stringify(body),
  });
  if (resp.status === 401) { renderLogin(); throw new Error("unauthenticated"); }
  if (!resp.ok) throw new Error(`${method} ${path}: HTTP ${resp.status}`);
  const text = await resp.text();
  return text ? JSON.parse(text) : null;
}

// Generated path layer (webui/api_client.js, from proto/openapi.json).
const API = makeApiClient(api);

// ---------------------------------------------------------------- util

function el(tag, attrs = {}, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === "class") node.className = v;
    else if (k.startsWith("on")) node.addEventListener(k.slice(2), v);
    else node.setAttribute(k, v);
  }
  for (const c of children.flat()) {
    node.append(c instanceof Node ? c : document.createTextNode(String(c)));
  }
  return node;
}

function stateBadge(s) { return el("span", { class: `state ${s}` }, s); }

// Same client-side salted hash as the CLI/SDK (common/api.py salted_hash):
// the master stores/compares the opaque digest, raw passwords stay off the
// wire. Empty password maps to "" (bootstrap-user posture).
async function saltedHash(username, password) {
  if (!password) return "";
  const data = new TextEncoder().encode(
    `determined-tpu$${username}$${password}`);
  const digest = await crypto.subtle.digest("SHA-256", data);
  return [...new Uint8Array(digest)]
    .map((b) => b.toString(16).padStart(2, "0")).join("");
}

// Long-poll /api/v1/stream and invoke cb(events) until the page changes.
// Resyncs (cb(null)) when the master reports a dropped cursor.
async function followStream(entities, cb) {
  const myGen = gen;
  let since = 0;
  while (myGen === gen) {
    try {
      const out = await API.getStream(
        { since, entities, timeout_seconds: 25 });
      if (myGen !== gen) return;
      if (out.dropped) { since = 0; cb(null); continue; }
      if (out.events.length) { since = out.latest_seq; cb(out.events); }
    } catch (e) {
      if (e.message === "unauthenticated") return;
      await new Promise((r) => setTimeout(r, 2000));
    }
  }
}

function fmt(v) {
  if (typeof v !== "number") return String(v);
  if (Number.isInteger(v)) return String(v);
  const a = Math.abs(v);
  if (a !== 0 && (a < 1e-3 || a >= 1e5)) return v.toExponential(3);
  return v.toPrecision(4);
}

// ---------------------------------------------------------------- chart

const SERIES_VARS = ["--series-1", "--series-2", "--series-3", "--series-4"];

function seriesColor(i) {
  const css = getComputedStyle(document.body);
  return css.getPropertyValue(SERIES_VARS[i % SERIES_VARS.length]).trim();
}

// series: [{name, points: [{x, y}]}]. The SVG plots at most 4 (fixed-order
// categorical slots, never cycled); the table view keeps ALL series so
// nothing is silently dropped, and the legend notes any fold.
function lineChart(title, series, xLabel) {
  const allSeries = series.filter((s) => s.points.length > 0);
  series = allSeries.slice(0, 4);
  const folded = allSeries.length - series.length;
  const W = 720, H = 240, M = { l: 56, r: 110, t: 12, b: 28 };
  const block = el("div", { class: "chart-block" });
  const head = el("div", { class: "chart-head" },
    el("span", { class: "chart-title" }, title));
  if (series.length >= 2 || folded > 0) {
    const legend = el("span", { class: "legend" },
      series.map((s, i) => el("span", {},
        el("span", { class: "swatch",
                     style: `background:${seriesColor(i)}` }), s.name)));
    if (folded > 0) {
      legend.append(el("span", { class: "muted" },
        `+${folded} more in table view`));
    }
    head.append(legend);
  }
  const tableBtn = el("button", { class: "table-toggle" }, "table view");
  head.append(tableBtn);
  block.append(head);
  if (series.length === 0) {
    block.append(el("div", { class: "muted" }, "no data"));
    return block;
  }

  const xs = series.flatMap((s) => s.points.map((p) => p.x));
  const ys = series.flatMap((s) => s.points.map((p) => p.y));
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const xpad = xmax === xmin ? 1 : 0;
  const ypad = (ymax - ymin || Math.abs(ymax) || 1) * 0.08;
  const sx = (x) => M.l + ((x - xmin) / (xmax - xmin + xpad)) * (W - M.l - M.r);
  const sy = (y) => H - M.b -
    ((y - (ymin - ypad)) / ((ymax + ypad) - (ymin - ypad))) * (H - M.t - M.b);

  const NS = "http://www.w3.org/2000/svg";
  const svg = document.createElementNS(NS, "svg");
  svg.setAttribute("class", "chart");
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);

  // recessive grid: 4 horizontal lines + y labels
  for (let i = 0; i <= 3; i++) {
    const y = ymin - ypad + ((ymax + ypad) - (ymin - ypad)) * (i / 3);
    const line = document.createElementNS(NS, "line");
    line.setAttribute("class", "gridline");
    line.setAttribute("x1", M.l); line.setAttribute("x2", W - M.r);
    line.setAttribute("y1", sy(y)); line.setAttribute("y2", sy(y));
    svg.append(line);
    const label = document.createElementNS(NS, "text");
    label.setAttribute("class", "axis-label");
    label.setAttribute("x", M.l - 6); label.setAttribute("y", sy(y) + 4);
    label.setAttribute("text-anchor", "end");
    label.textContent = fmt(y);
    svg.append(label);
  }
  const xl = document.createElementNS(NS, "text");
  xl.setAttribute("class", "axis-label");
  xl.setAttribute("x", (M.l + W - M.r) / 2); xl.setAttribute("y", H - 8);
  xl.setAttribute("text-anchor", "middle");
  xl.textContent = xLabel || "batches";
  svg.append(xl);

  series.forEach((s, i) => {
    if (s.points.length === 1) {
      // a lone M command paints nothing — draw a marker instead
      const dot = document.createElementNS(NS, "circle");
      dot.setAttribute("cx", sx(s.points[0].x));
      dot.setAttribute("cy", sy(s.points[0].y));
      dot.setAttribute("r", 4);
      dot.setAttribute("fill", seriesColor(i));
      svg.append(dot);
    } else {
      const path = document.createElementNS(NS, "path");
      path.setAttribute("class", "series-line");
      path.setAttribute("stroke", seriesColor(i));
      path.setAttribute("d", s.points.map((p, j) =>
        `${j ? "L" : "M"}${sx(p.x).toFixed(1)},${sy(p.y).toFixed(1)}`).join(""));
      svg.append(path);
    }
    // direct label at line end (text wears text tokens, swatch carries hue)
    const last = s.points[s.points.length - 1];
    const lbl = document.createElementNS(NS, "text");
    lbl.setAttribute("class", "direct-label axis-label");
    lbl.setAttribute("x", sx(last.x) + 6);
    lbl.setAttribute("y", sy(last.y) + 4);
    lbl.textContent = `${s.name} ${fmt(last.y)}`;
    svg.append(lbl);
  });

  // hover layer: crosshair + nearest-x tooltip
  const cross = document.createElementNS(NS, "line");
  cross.setAttribute("class", "crosshair");
  cross.setAttribute("y1", M.t); cross.setAttribute("y2", H - M.b);
  cross.style.display = "none";
  svg.append(cross);
  const dots = series.map((s, i) => {
    const d = document.createElementNS(NS, "circle");
    d.setAttribute("class", "hover-dot");
    d.setAttribute("r", 4);
    d.setAttribute("fill", seriesColor(i));
    d.style.display = "none";
    svg.append(d);
    return d;
  });
  const tooltip = el("div", { class: "tooltip" });
  const wrap = el("div", { class: "chart-wrap" }, svg, tooltip);
  svg.addEventListener("mousemove", (ev) => {
    const rect = svg.getBoundingClientRect();
    const px = ((ev.clientX - rect.left) / rect.width) * W;
    if (px < M.l || px > W - M.r) { return; }
    let bestX = null, bestD = Infinity;
    for (const x of new Set(xs)) {
      const d = Math.abs(sx(x) - px);
      if (d < bestD) { bestD = d; bestX = x; }
    }
    cross.setAttribute("x1", sx(bestX)); cross.setAttribute("x2", sx(bestX));
    cross.style.display = "";
    const lines = [`${xLabel || "batches"} ${fmt(bestX)}`];
    series.forEach((s, i) => {
      const p = s.points.find((q) => q.x === bestX);
      if (p) {
        dots[i].setAttribute("cx", sx(p.x));
        dots[i].setAttribute("cy", sy(p.y));
        dots[i].style.display = "";
        lines.push(`${s.name}: ${fmt(p.y)}`);
      } else {
        dots[i].style.display = "none";
      }
    });
    tooltip.style.display = "block";
    tooltip.textContent = "";
    lines.forEach((l) => tooltip.append(el("div", {}, l)));
    const tx = (sx(bestX) / W) * rect.width;
    tooltip.style.left = `${Math.min(tx + 12, rect.width - 150)}px`;
    tooltip.style.top = "10px";
  });
  svg.addEventListener("mouseleave", () => {
    cross.style.display = "none";
    tooltip.style.display = "none";
    dots.forEach((d) => (d.style.display = "none"));
  });
  block.append(wrap);

  // accessible table view — ALL series, including any folded past slot 4
  const txs = [...new Set(allSeries.flatMap((s) => s.points.map((p) => p.x)))]
    .sort((a, b) => a - b);
  const table = el("table", { class: "datatable" },
    el("tr", {}, el("th", {}, xLabel || "batches"),
      allSeries.map((s) => el("th", {}, s.name))),
    txs.map((x) =>
      el("tr", {}, el("td", {}, fmt(x)),
        allSeries.map((s) => {
          const p = s.points.find((q) => q.x === x);
          return el("td", {}, p ? fmt(p.y) : "");
        }))));
  table.style.display = "none";
  block.append(table);
  tableBtn.addEventListener("click", () => {
    const show = table.style.display === "none";
    table.style.display = show ? "block" : "none";
    wrap.style.display = show ? "none" : "block";
    tableBtn.textContent = show ? "chart view" : "table view";
  });
  return block;
}

// scatter: points [{x, y, label}] — hparam-vs-metric view for HP search.
function scatterChart(title, points, xLabel, yLabel) {
  const W = 720, H = 240, M = { l: 64, r: 24, t: 12, b: 32 };
  const block = el("div", { class: "chart-block" },
    el("div", { class: "chart-head" },
      el("span", { class: "chart-title" }, title)));
  if (!points.length) {
    block.append(el("div", { class: "muted" }, "no data"));
    return block;
  }
  const xs = points.map((p) => p.x), ys = points.map((p) => p.y);
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const xpad = (xmax - xmin || Math.abs(xmax) || 1) * 0.06;
  const ypad = (ymax - ymin || Math.abs(ymax) || 1) * 0.1;
  const sx = (x) => M.l + ((x - (xmin - xpad)) /
    ((xmax + xpad) - (xmin - xpad))) * (W - M.l - M.r);
  const sy = (y) => H - M.b - ((y - (ymin - ypad)) /
    ((ymax + ypad) - (ymin - ypad))) * (H - M.t - M.b);
  const NS = "http://www.w3.org/2000/svg";
  const svg = document.createElementNS(NS, "svg");
  svg.setAttribute("class", "chart");
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
  for (let i = 0; i <= 3; i++) {
    const y = ymin - ypad + ((ymax + ypad) - (ymin - ypad)) * (i / 3);
    const line = document.createElementNS(NS, "line");
    line.setAttribute("class", "gridline");
    line.setAttribute("x1", M.l); line.setAttribute("x2", W - M.r);
    line.setAttribute("y1", sy(y)); line.setAttribute("y2", sy(y));
    svg.append(line);
    const lbl = document.createElementNS(NS, "text");
    lbl.setAttribute("class", "axis-label");
    lbl.setAttribute("x", M.l - 6); lbl.setAttribute("y", sy(y) + 4);
    lbl.setAttribute("text-anchor", "end");
    lbl.textContent = fmt(y);
    svg.append(lbl);
  }
  const xl = document.createElementNS(NS, "text");
  xl.setAttribute("class", "axis-label");
  xl.setAttribute("x", (M.l + W - M.r) / 2); xl.setAttribute("y", H - 8);
  xl.setAttribute("text-anchor", "middle");
  xl.textContent = `${xLabel}  →  ${yLabel}`;
  svg.append(xl);
  for (const p of points) {
    const dot = document.createElementNS(NS, "circle");
    dot.setAttribute("cx", sx(p.x)); dot.setAttribute("cy", sy(p.y));
    dot.setAttribute("r", 4.5);
    dot.setAttribute("fill", seriesColor(0));
    dot.append((() => {
      const t = document.createElementNS(NS, "title");
      t.textContent = `${p.label}: ${xLabel}=${fmt(p.x)} ${yLabel}=${fmt(p.y)}`;
      return t;
    })());
    svg.append(dot);
  }
  block.append(el("div", { class: "chart-wrap" }, svg));
  return block;
}


// Parallel coordinates (reference HP-viz): one vertical axis per numeric
// hyperparameter + the searcher metric; one polyline per scored trial,
// best-metric trial drawn in the accent series color, others recessive.
function parallelCoords(trials, hpNames, metricName, smallerBetter) {
  const scored = trials.filter((t) => t.searcher_metric_value != null);
  // dims from SCORED trials only: an hp numeric solely on unscored
  // trials would give empty ranges (Infinity ticks, zero polylines).
  const dims = hpNames.filter((h) =>
    scored.some((t) => typeof (t.hparams || {})[h] === "number"));
  if (dims.length < 1 || scored.length < 2) return null;
  const axes = [...dims.map((h) => ({
    name: h, get: (t) => (t.hparams || {})[h],
  })), { name: metricName, get: (t) => t.searcher_metric_value }];
  const W = 720, H = 260, M = { l: 40, r: 40, t: 28, b: 12 };
  const NS = "http://www.w3.org/2000/svg";
  const svg = document.createElementNS(NS, "svg");
  svg.setAttribute("class", "chart");
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
  const ax = (i) => M.l + (i / (axes.length - 1)) * (W - M.l - M.r);
  const ranges = axes.map((a) => {
    const vs = scored.map(a.get).filter((v) => typeof v === "number");
    const lo = Math.min(...vs), hi = Math.max(...vs);
    return { lo, hi: hi === lo ? lo + 1 : hi };
  });
  axes.forEach((a, i) => {
    const line = document.createElementNS(NS, "line");
    line.setAttribute("class", "gridline");
    line.setAttribute("x1", ax(i)); line.setAttribute("x2", ax(i));
    line.setAttribute("y1", M.t); line.setAttribute("y2", H - M.b);
    svg.append(line);
    const lab = document.createElementNS(NS, "text");
    lab.setAttribute("class", "axis-label");
    lab.setAttribute("x", ax(i)); lab.setAttribute("y", M.t - 8);
    lab.setAttribute("text-anchor", "middle");
    lab.textContent = a.name;
    svg.append(lab);
    for (const [v, anchor] of [[ranges[i].lo, H - M.b], [ranges[i].hi, M.t + 10]]) {
      const tick = document.createElementNS(NS, "text");
      tick.setAttribute("class", "axis-label");
      tick.setAttribute("x", ax(i) + 4); tick.setAttribute("y", anchor);
      tick.textContent = fmt(v);
      svg.append(tick);
    }
  });
  const best = (smallerBetter ? Math.min : Math.max)(
    ...scored.map((t) => t.searcher_metric_value));
  for (const t of scored) {
    const pts = axes.map((a, i) => {
      const v = a.get(t);
      if (typeof v !== "number") return null;
      const y = H - M.b -
        ((v - ranges[i].lo) / (ranges[i].hi - ranges[i].lo)) * (H - M.t - M.b);
      return `${ax(i).toFixed(1)},${y.toFixed(1)}`;
    });
    if (pts.some((s) => s === null)) continue;
    const path = document.createElementNS(NS, "path");
    const isBest = t.searcher_metric_value === best;
    path.setAttribute("class", "series-line");
    path.setAttribute("stroke", isBest ? seriesColor(0) : seriesColor(1));
    path.setAttribute("stroke-opacity", isBest ? "1" : "0.35");
    path.setAttribute("fill", "none");
    path.setAttribute("d", "M" + pts.join("L"));
    path.append(Object.assign(document.createElementNS(NS, "title"), {
      textContent: `trial ${t.id}: ${fmt(t.searcher_metric_value)}` }));
    svg.append(path);
  }
  const block = el("div", { class: "chart-block" },
    el("div", { class: "chart-head" },
      el("span", { class: "chart-title" },
        `parallel coordinates (best trial highlighted)`)));
  block.append(el("div", { class: "chart-wrap" }, svg));
  return block;
}

// ---------------------------------------------------------------- pages

function renderLogin(err) {
  view.textContent = "";
  const user = el("input", { placeholder: "username", value: "determined" });
  const pass = el("input", { placeholder: "password", type: "password" });
  const msg = el("div", { class: "error" }, err || "");
  const form = el("div", { id: "login" },
    el("h1", {}, "Sign in"), user, pass,
    el("button", {
      onclick: async () => {
        try {
          const r = await fetch("/api/v1/auth/login", {
            method: "POST",
            headers: { "Content-Type": "application/json" },
            body: JSON.stringify({
              username: user.value,
              password: await saltedHash(user.value, pass.value),
            }),
          });
          if (!r.ok) throw new Error(`HTTP ${r.status}`);
          const j = await r.json();
          localStorage.setItem("det_token", j.token);
          localStorage.setItem("det_user", user.value);
          route();
        } catch (e) { msg.textContent = `login failed: ${e.message}`; }
      },
    }, "Log in"), msg);
  view.append(form);
}

const PAGE_SIZE = 50;
let expOffset = 0;  // survives stream-driven re-renders

async function pageExperiments(offset = expOffset) {
  expOffset = offset;
  const { experiments, pagination } = await API.getExperiments(
    { limit: PAGE_SIZE, offset });
  view.textContent = "";
  view.append(el("h1", {}, "Experiments"));
  const rows = experiments.map((e) => el("tr", {
    class: "rowlink",
    onclick: () => { location.hash = `#/experiments/${e.id}`; },
  },
    el("td", {}, e.id),
    el("td", {}, e.name ?? ""),
    el("td", {}, stateBadge(e.state)),
    el("td", {}, `${Math.round((e.progress ?? 0) * 100)}%`),
    el("td", {}, e.config?.searcher?.name ?? ""),
    el("td", { class: "muted" }, e.config?.resources?.slots_per_trial ?? 1)));
  view.append(el("table", {},
    el("tr", {}, ["ID", "Name", "State", "Progress", "Searcher", "Slots"]
      .map((h) => el("th", {}, h))), rows));
  if (!experiments.length) view.append(el("p", { class: "muted" }, "no experiments"));
  const total = pagination?.total ?? experiments.length;
  if (total > PAGE_SIZE) {
    const newer = el("button", {
      onclick: () => pageExperiments(Math.max(0, offset - PAGE_SIZE)) },
      "\u2039 newer");
    if (offset === 0) newer.disabled = true;
    const older = el("button", {
      onclick: () => pageExperiments(offset + PAGE_SIZE) }, "older \u203a");
    if (offset + PAGE_SIZE >= total) older.disabled = true;
    view.append(el("div", { class: "pager" }, newer,
      el("span", { class: "muted" },
        ` ${offset + 1}\u2013${offset + experiments.length} of ${total} `),
      older));
  }
}

async function pageExperiment(id) {
  const [{ experiment }, { trials }] = await Promise.all([
    API.getExperimentsId(id),
    API.getExperimentsIdTrials(id),
  ]);
  view.textContent = "";
  view.append(el("h1", {}, `Experiment ${id} `, stateBadge(experiment.state),
    el("span", { class: "muted" }, `  ${experiment.name ?? ""}`)));

  const actions = el("div", { class: "actions" });
  const actErr = el("span", { class: "error" });
  const act = (label, method, path) => el("button", {
    onclick: async () => {
      try {
        await api(method, path);
        pageExperiment(id);
      } catch (e) { actErr.textContent = `${label} failed: ${e.message}`; }
    },
  }, label);
  if (experiment.state === "ACTIVE" || experiment.state === "RUNNING") {
    actions.append(act("Pause", "POST", `/api/v1/experiments/${id}/pause`));
  }
  if (experiment.state === "PAUSED") {
    actions.append(act("Activate", "POST", `/api/v1/experiments/${id}/activate`));
  }
  if (!["COMPLETED", "CANCELED", "ERROR", "DELETED"].includes(experiment.state)) {
    actions.append(act("Kill", "POST", `/api/v1/experiments/${id}/kill`));
  }
  actions.append(actErr);
  view.append(actions);

  // HP search view: hparams per trial + searcher metric, with an
  // hparam-vs-metric scatter per numeric hyperparameter (the reference's
  // HP-viz pages in webui/react/src/pages/ExperimentDetails).
  const metricName = experiment.config?.searcher?.metric || "metric";
  const hpNames = [...new Set(trials.flatMap(
    (t) => Object.keys(t.hparams || {})))].sort();
  view.append(el("h2", {}, "Trials"));
  const elastic = experiment.config?.resources?.elastic;
  view.append(el("table", {},
    el("tr", {}, ["ID", "State", "Batches", ...hpNames, metricName,
                  "Slots", "Restarts", "Logs"].map((h) => el("th", {}, h))),
    trials.map((t) => el("tr", {},
      el("td", {}, t.id), el("td", {}, stateBadge(t.state)),
      el("td", {}, t.total_batches ?? 0),
      hpNames.map((h) => el("td", { class: "muted" },
        t.hparams && h in t.hparams ? fmt(t.hparams[h]) : "")),
      el("td", {}, t.searcher_metric_value == null
        ? "" : fmt(t.searcher_metric_value)),
      // Elastic trials may run below/above their preferred size; show the
      // size the trial holds RIGHT NOW (docs/elasticity.md).
      el("td", elastic ? { title:
        `elastic ${elastic.min_slots ?? 1}–${elastic.max_slots ?? "?"}` } : {},
        t.current_slots ??
          (experiment.config?.resources?.slots_per_trial ?? 1)),
      el("td", {}, t.restarts ?? 0),
      el("td", {}, el("a", { href: `#/trials/${t.id}` }, "logs"))))));

  const scored = trials.filter((t) => t.searcher_metric_value != null);
  if (scored.length >= 2) {
    view.append(el("h2", {}, "Hyperparameter search"));
    const pcChart = parallelCoords(
      trials, hpNames, metricName,
      experiment.config?.searcher?.smaller_is_better !== false);
    if (pcChart) view.append(pcChart);
    for (const h of hpNames) {
      const pts = scored
        .filter((t) => typeof (t.hparams || {})[h] === "number")
        .map((t) => ({ x: t.hparams[h], y: t.searcher_metric_value,
                       label: `trial ${t.id}` }));
      if (pts.length >= 2) {
        view.append(scatterChart(`${h} vs ${metricName}`, pts, h, metricName));
      }
    }
  }

  // Trial comparison: the searcher metric's curve per trial, overlaid
  // (ASHA rungs become visibly different lengths). lineChart folds >4
  // series into the table view so nothing is dropped silently.
  if (trials.length >= 2) {
    const metricLists = await Promise.all(trials.slice(0, 12).map((t) =>
      API.getTrialsIdMetrics(t.id, { group: "validation" })));
    const series = [];
    trials.slice(0, 12).forEach((t, i) => {
      const pts = [];
      for (const m of metricLists[i].metrics) {
        for (const key of [metricName, `validation_${metricName}`]) {
          const v = (m.metrics || {})[key];
          if (typeof v === "number" && isFinite(v)) {
            pts.push({ x: m.total_batches, y: v });
          }
        }
      }
      if (pts.length) series.push({ name: `trial ${t.id}`, points: pts });
    });
    if (series.length >= 2) {
      view.append(el("h2", {}, "Trial comparison"));
      view.append(lineChart(`${metricName} by trial`, series));
      if (trials.length > 12) {
        view.append(el("p", { class: "muted" },
          `first 12 of ${trials.length} trials shown`));
      }
    }
  }

  // metric charts from the first trial (single/first-trial view; the data
  // is per-trial at /api/v1/trials/{id}/metrics)
  if (trials.length) {
    const { metrics } = await API.getTrialsIdMetrics(trials[0].id);
    const groups = {};
    for (const m of metrics) {
      for (const [k, v] of Object.entries(m.metrics || {})) {
        if (typeof v !== "number" || !isFinite(v)) continue;
        const key = `${m.group_name}:${k}`;
        (groups[key] ??= []).push({ x: m.total_batches, y: v });
      }
    }
    view.append(el("h2", {}, `Metrics (trial ${trials[0].id})`));
    const lossSeries = [];
    for (const name of ["training:loss", "validation:validation_loss",
                        "validation:val_loss", "validation:loss"]) {
      if (groups[name]) {
        lossSeries.push({ name: name.replace(":", " "), points: groups[name] });
        delete groups[name];
      }
    }
    if (lossSeries.length) view.append(lineChart("loss", lossSeries));
    // remaining numeric series, one small chart each (single series → no
    // legend; the title names it); cap at 6 charts and SAY so
    const entries = Object.entries(groups);
    for (const [name, points] of entries.slice(0, 6)) {
      view.append(lineChart(name.replace(":", " "), [{ name, points }]));
    }
    if (entries.length > 6) {
      view.append(el("p", { class: "muted" },
        `+${entries.length - 6} more metric series: ` +
        entries.slice(6).map(([n]) => n.replace(":", " ")).join(", ")));
    }
  }

  // Checkpoints (registry view; GC'd ones show as DELETED)
  const { checkpoints } = await API.getExperimentsIdCheckpoints(id);
  if (checkpoints.length) {
    view.append(el("h2", {}, "Checkpoints"));
    view.append(el("table", {},
      el("tr", {}, ["UUID", "Trial", "Steps", "State", "Reported"]
        .map((h) => el("th", {}, h))),
      checkpoints.map((c) => el("tr", {},
        el("td", { class: "muted" }, c.uuid),
        el("td", {}, c.trial_id ?? ""),
        el("td", {}, c.steps_completed ?? 0),
        el("td", {}, stateBadge(c.state)),
        el("td", { class: "muted" }, c.report_time ?? "")))));
  }

  // Model-definition file listing (content-cached server-side). The
  // fetch is best-effort (unreadable tarball → 500); rendering stays
  // OUTSIDE the catch so real UI bugs surface.
  let fileTree = null;
  try {
    fileTree = (await API.getExperimentsIdFileTree(id)).files;
  } catch (e) { console.warn("file_tree unavailable:", e.message); }
  if (fileTree && fileTree.length) {
    view.append(el("h2", {}, "Files"));
    view.append(el("table", {},
      el("tr", {}, ["Path", "Bytes"].map((h) => el("th", {}, h))),
      fileTree.map((f) => el("tr", {},
        el("td", { class: "muted" }, f.path),
        el("td", {}, f.size)))));
  }

  view.append(el("h2", {}, "Config"));
  view.append(el("pre", { class: "config" },
    JSON.stringify(experiment.config, null, 2)));
}

async function pageTrial(id) {
  const myGen = gen;
  const { trial } = await API.getTrialsId(id);
  view.textContent = "";
  view.append(el("h1", {},
    el("a", { href: `#/experiments/${trial.experiment_id}` },
      `Experiment ${trial.experiment_id}`),
    ` / Trial ${id} `, stateBadge(trial.state)));
  view.append(el("p", { class: "muted" },
    `batches ${trial.total_batches ?? 0} · restarts ${trial.restarts ?? 0}` +
    (trial.current_slots != null ? ` · slots ${trial.current_slots}` : "") +
    (trial.latest_checkpoint ? ` · checkpoint ${trial.latest_checkpoint}` : "")));
  // Elastic size history (docs/elasticity.md): each shrink/grow the
  // scheduler put this trial through, with the drain/scale-up reason.
  if ((trial.size_history ?? []).length) {
    view.append(el("h2", {}, "Size history"));
    view.append(el("table", {},
      el("tr", {}, ["When", "Allocation", "From", "To", "Reason"]
        .map((h) => el("th", {}, h))),
      trial.size_history.map((ev) => el("tr", {},
        el("td", { class: "muted" }, ev.created_at ?? ""),
        el("td", { class: "muted" }, ev.allocation_id ?? ""),
        el("td", {}, ev.from_slots),
        el("td", {}, ev.to_slots),
        el("td", { class: "muted" }, ev.reason ?? "")))));
  }

  // Lifecycle-trace waterfall (docs/observability.md): where this trial's
  // wall-clock went — queue wait, container start, compile, restore,
  // checkpoints, validation — straight from GET /trials/{id}/trace.
  try {
    const { spans } = await API.getTrialsIdTrace(id);
    if ((spans ?? []).length) {
      view.append(el("h2", {}, "Trace"));
      const t0 = Math.min(...spans.map((s) => s.start_us));
      const t1 = Math.max(t0 + 1, ...spans.map((s) => s.end_us || 0));
      const byId = Object.fromEntries(spans.map((s) => [s.span_id, s]));
      const depth = (s) => {
        let d = 0;
        for (let cur = s; d < 16; d++) {
          const p = byId[cur.parent];
          if (!p || p === cur) break;
          cur = p;
        }
        return d;
      };
      view.append(el("div", { class: "waterfall" }, spans.map((s) => {
        const left = ((s.start_us - t0) / (t1 - t0)) * 100;
        const end = s.end_us || t1;
        const width = Math.max(((end - s.start_us) / (t1 - t0)) * 100, 0.5);
        const durMs = s.end_us ? ((s.end_us - s.start_us) / 1000) : null;
        return el("div", { class: "waterfall-row" },
          el("span", { class: "waterfall-name",
                       style: `padding-left:${depth(s) * 12}px` }, s.name),
          el("span", { class: "waterfall-track" },
            el("span", {
              class: `waterfall-bar ${s.end_us ? "" : "open"}`,
              style: `left:${left}%;width:${width}%`,
              title: `${s.name}: ` + (durMs != null
                ? `${durMs.toFixed(1)} ms` : "still open"),
            })),
          el("span", { class: "waterfall-dur muted" },
            durMs != null ? `${durMs.toFixed(1)} ms` : "…"));
      })));
    }
  } catch (e) {
    // Pre-migration masters have no trace route; the trial page must
    // still render.
  }

  // Log viewer with follow (reference TrialLogs page; long-polls the
  // master's follow endpoint so new lines stream in live).
  const followBox = el("input", { type: "checkbox", checked: "checked" });
  view.append(el("h2", {}, "Logs ",
    el("label", { class: "muted" }, followBox, " follow")));
  const pane = el("pre", { class: "logpane" });
  view.append(pane);
  let offset = 0;
  const pump = async () => {
    while (myGen === gen) {
      const follow = followBox.checked;
      const { logs } = await API.getTasksIdLogs(
        `trial-${id}`, { offset, follow, timeout_seconds: 20 });
      if (myGen !== gen) return;
      for (const line of logs) {
        offset = Math.max(offset, line.id);
        pane.append(el("div", { class: `loglevel-${line.level || "INFO"}` },
          `${line.timestamp ?? ""}  ${line.log}`));
      }
      if (logs.length && followBox.checked) pane.scrollTop = pane.scrollHeight;
      if (!follow) {
        if (!logs.length) return;  // drained; stop without follow
      } else if (!logs.length) {
        await new Promise((r) => setTimeout(r, 1000));
      }
    }
  };
  pump().catch((e) => {
    if (myGen === gen) pane.append(el("div", { class: "error" }, String(e)));
  });
}

async function pageWorkspaces() {
  const { workspaces } = await API.getWorkspaces();
  view.textContent = "";
  view.append(el("h1", {}, "Workspaces"));
  for (const w of workspaces) {
    if (w.archived) continue;
    const { projects } = await API.getWorkspacesIdProjects(w.id);
    view.append(el("h2", {}, `${w.name} `,
      el("span", { class: "muted" }, `(id ${w.id})`)));
    view.append(el("table", {},
      el("tr", {}, ["Project", "Description", "Experiments"]
        .map((h) => el("th", {}, h))),
      projects.filter((p) => !p.archived).map((p) => el("tr", {},
        el("td", {}, p.name),
        el("td", { class: "muted" }, p.description ?? ""),
        el("td", {}, el("a", {
          href: `#/experiments`,
          onclick: () => sessionStorage.setItem("project_filter", p.id),
        }, "view"))))));
  }
  if (!workspaces.length) view.append(el("p", { class: "muted" }, "none"));
}

async function pageModels() {
  const { models } = await API.getModels();
  view.textContent = "";
  view.append(el("h1", {}, "Model registry"));
  if (!models.length) {
    view.append(el("p", { class: "muted" }, "no registered models"));
    return;
  }
  for (const m of models) {
    if (m.archived) continue;
    const { model_versions } = await API.getModelsNameVersions(
      encodeURIComponent(m.name));
    view.append(el("h2", {}, m.name,
      el("span", { class: "muted" }, `  ${m.description ?? ""}`)));
    view.append(el("table", {},
      el("tr", {}, ["Version", "Checkpoint", "Source", "Registered"]
        .map((h) => el("th", {}, h))),
      model_versions.map((v) => {
        // Train→serve provenance (docs/serving.md "Model lifecycle"):
        // which experiment/trial/step produced this version.
        const src = v.source_experiment_id
          ? `exp ${v.source_experiment_id} · trial ${v.source_trial_id}` +
            (v.steps_completed != null ? ` @ ${v.steps_completed}` : "")
          : "";
        const row = el("tr", { class: "rowlink" },
          el("td", {}, v.version),
          el("td", { class: "muted" }, v.checkpoint_uuid),
          el("td", { class: "muted" }, src),
          el("td", { class: "muted" }, v.creation_time ?? ""));
        row.addEventListener("click", async () => {
          // Version detail: the backing checkpoint's metadata/resources,
          // toggled inline (reference ModelVersionDetails page).
          if (row.nextSibling?.classList?.contains("version-detail")) {
            row.nextSibling.remove();
            return;
          }
          const { checkpoint } = await API.getCheckpointsUuid(
            v.checkpoint_uuid);
          row.after(el("tr", { class: "version-detail" },
            el("td", { colspan: 4 }, el("pre", { class: "config" },
              JSON.stringify({
                trial_id: checkpoint.trial_id,
                steps_completed: checkpoint.steps_completed,
                state: checkpoint.state,
                metadata: checkpoint.metadata,
                resources: checkpoint.resources,
              }, null, 2)))));
        });
        return row;
      })));
  }
}

async function pageUsers() {
  const [{ users }, me, { assignments }] = await Promise.all([
    API.getUsers(),
    API.getMe(),
    API.getRbacAssignments(),
  ]);
  const admin = me.user.role === "admin";
  view.textContent = "";
  view.append(el("h1", {}, "Users"));
  const err = el("span", { class: "error" });
  const act = (label, fn) => el("button", {
    onclick: async () => {
      try { await fn(); pageUsers(); }
      catch (e) { err.textContent = `${label} failed: ${e.message}`; }
    },
  }, label);
  view.append(el("table", {},
    el("tr", {}, ["ID", "Username", "Role", "Active",
                  ...(admin ? ["Admin actions"] : [])]
      .map((h) => el("th", {}, h))),
    users.map((u) => el("tr", {},
      el("td", {}, u.id),
      el("td", {}, u.username),
      el("td", {}, u.role),
      el("td", {}, u.active ? "yes" : "no"),
      ...(admin ? [el("td", {},
        act(u.active ? "deactivate" : "activate", () =>
          API.patchUsersId(u.id, { active: !u.active })),
        " ",
        act("make viewer", () =>
          API.patchUsersId(u.id, { role: "viewer" })),
        " ",
        act("make user", () =>
          API.patchUsersId(u.id, { role: "user" })),
        " ",
        act("make admin", () =>
          API.patchUsersId(u.id, { role: "admin" })))]
        : [])))));
  if (admin) {
    const name = el("input", { placeholder: "username" });
    const role = el("select", {},
      ["user", "viewer", "admin"].map((r) => el("option", { value: r }, r)));
    view.append(el("div", { class: "actions" }, name, role,
      act("create user", async () => {
        await api("POST", "/api/v1/users",
                  { username: name.value, role: role.value });
      }), err));
  } else {
    view.append(el("p", { class: "muted" },
      "admin role required for user management"));
  }

  view.append(el("h2", {}, "Role assignments"));
  view.append(el("table", {},
    el("tr", {}, ["ID", "Role", "User", "Group", "Workspace",
                  ...(admin ? [""] : [])].map((h) => el("th", {}, h))),
    assignments.map((a) => el("tr", {},
      el("td", {}, a.id), el("td", {}, a.role),
      el("td", {}, a.username ?? ""), el("td", {}, a.group_name ?? ""),
      el("td", {}, a.workspace_id ?? "global"),
      ...(admin ? [el("td", {}, act("revoke", () =>
        API.deleteRbacAssignmentsId(a.id)))] : [])))));
  if (!assignments.length) {
    view.append(el("p", { class: "muted" }, "no grants"));
  }
}

async function pageCluster() {
  const { agents } = await API.getAgents();
  view.textContent = "";
  view.append(el("h1", {}, "Cluster"));
  view.append(el("table", {},
    el("tr", {}, ["Agent", "Pool", "Class", "Address", "Alive", "Lease", "State", "Slots (chips)"]
      .map((h) => el("th", {}, h))),
    agents.map((a) => el("tr", {},
      el("td", {}, a.id),
      el("td", {}, a.resource_pool),
      // Spot badge: preemptible capacity is reclaimable surplus — a
      // deployment's on_demand_floor replicas never land here.
      el("td", {}, a.preemptible
        ? el("span", { class: "badge spot", title: "preemptible (spot) capacity" }, "spot")
        : "on-demand"),
      el("td", { class: "muted" }, a.addr),
      el("td", {}, a.alive ? "yes" : "no"),
      // Ownership lease (docs/cluster-ops.md "Leases, fencing &
      // split-brain"): time until the master counts this agent's lease
      // lapsed and expects its tasks self-fenced.
      el("td", a.lease_expired
        ? { class: "muted", title: "lease lapsed; agent should have self-fenced its tasks" }
        : {},
        a.lease_expired ? "expired"
          : `${Math.max(0, a.lease_remaining_seconds ?? 0).toFixed(0)}s`),
      el("td", a.state === "DRAINING" ? { title: a.drain_reason } : {},
        a.state === "DRAINING" ? `draining (${a.drain_reason})`
          : (a.state || "ENABLED").toLowerCase()),
      el("td", {}, el("span", { class: "slots" },
        a.slots.map((s) => el("span", {
          class: `slot ${s.allocation_id ? "busy" : ""} ${s.enabled ? "" : "disabled"}`,
          title: `slot ${s.id}${s.allocation_id ? " → " + s.allocation_id : " (free)"}`,
        }))))))));
  if (!agents.length) view.append(el("p", { class: "muted" }, "no agents connected"));
}

async function pageJobs() {
  const { jobs } = await API.getJobQueues();
  view.textContent = "";
  view.append(el("h1", {}, "Job queue"));
  view.append(el("table", {},
    el("tr", {}, ["Allocation", "Experiment", "Pool", "Slots", "Priority",
                  "State", "Queue pos"].map((h) => el("th", {}, h))),
    jobs.map((j) => el("tr", {},
      el("td", { class: "muted" }, j.allocation_id),
      el("td", {}, j.experiment_id ?? ""),
      el("td", {}, j.resource_pool),
      el("td", {}, j.slots),
      el("td", {}, j.priority),
      el("td", {}, stateBadge(j.state)),
      el("td", {}, j.queue_position ?? "")))));
  if (!jobs.length) view.append(el("p", { class: "muted" }, "queue is empty"));
}

async function pageTasks() {
  const { tasks } = await API.getTasks();
  view.textContent = "";
  view.append(el("h1", {}, "Tasks"));
  const err = el("span", { class: "error" });
  const killable = (t) =>
    !["COMPLETED", "ERROR", "CANCELED"].includes(t.state);
  const killPath = {
    COMMAND: (id) => API.postCommandsIdKill(id),
    NOTEBOOK: (id) => API.postNotebooksIdKill(id),
    SHELL: (id) => API.postShellsIdKill(id),
    TENSORBOARD: (id) => API.postTensorboardsIdKill(id),
    GENERIC: (id) => API.postGenericTasksIdKill(id),
    SERVING: (id) => API.postServingIdKill(id),
  };
  view.append(el("table", {},
    el("tr", {}, ["ID", "Type", "State", "Started", "Ended", ""]
      .map((h) => el("th", {}, h))),
    tasks.map((t) => el("tr", {},
      el("td", {}, el("a", { href: `#/tasks/${t.id}` }, t.id)),
      el("td", {}, t.type),
      el("td", {}, stateBadge(
        ["COMPLETED", "ERROR", "CANCELED"].includes(t.state)
          ? t.state : (t.allocation_state ?? t.state))),
      el("td", { class: "muted" }, t.start_time ?? ""),
      el("td", { class: "muted" }, t.end_time ?? ""),
      el("td", {}, killable(t) && killPath[t.type] ? el("button", {
        onclick: async () => {
          try { await killPath[t.type](t.id); pageTasks(); }
          catch (e) { err.textContent = `kill failed: ${e.message}`; }
        } }, "kill") : "")))));
  if (!tasks.length) view.append(el("p", { class: "muted" }, "no tasks"));
  view.append(err);
}

async function pageServing() {
  const { serving } = await API.getServing();
  const { deployments } = await API.getDeployments();
  view.textContent = "";
  view.append(el("h1", {}, "Serving"));
  const err = el("span", { class: "error" });
  // Deployments (docs/serving.md "Deployments & autoscaling"): replica
  // sets behind the /serve/{id} router; +/- adjust target within
  // [min, max], the reconciler drains or spawns to match.
  // "p50/p99 ms" from the master's fresh-heartbeat latency aggregation
  // (docs/serving.md "Request latency & SLOs").
  const pp = (d, key) => {
    const h = (d.latency || {})[key] || {};
    return h.count ? `${h.p50_ms.toFixed(0)}/${h.p99_ms.toFixed(0)}` : "—";
  };
  // Model-lifecycle columns (docs/serving.md "Model lifecycle"): the
  // served version (→ marks an in-flight rolling swap) and the canary
  // split with its observed traffic fraction.
  const versionCell = (d) => {
    const v = (d.model_version || "").replace("checkpoint:", "ckpt:");
    return d.swapping ? `→ ${v}` : v;
  };
  const canaryCell = (d) => d.canary
    ? `${d.canary.version} @ ${d.canary.fraction}` +
      ` (obs ${(d.canary.observed_fraction ?? 0).toFixed(2)})`
    : "";
  if (deployments.length) {
    view.append(el("h2", {}, "Deployments"));
    view.append(el("table", {},
      el("tr", {}, ["ID", "Name", "State", "Replicas", "Range", "Version",
        "Canary", "Load",
        "TTFT p50/p99", "TPOT p50/p99", "e2e p50/p99", ""]
        .map((h) => el("th", {}, h))),
      deployments.map((d) => el("tr", {},
        el("td", {}, el("a", { href: `#/serving/${d.id}` }, d.id)),
        el("td", {}, d.name),
        el("td", {}, stateBadge(d.state)),
        el("td", {}, `${d.replica_count ?? 0}/${d.target_replicas}`),
        el("td", { class: "muted" },
          `[${d.min_replicas}, ${d.max_replicas}]`),
        el("td", { class: "muted" }, versionCell(d)),
        el("td", { class: "muted" }, canaryCell(d)),
        el("td", { class: "muted" },
          d.smoothed_load != null ? d.smoothed_load.toFixed(2) : ""),
        el("td", { class: "muted" }, pp(d, "ttft")),
        el("td", { class: "muted" }, pp(d, "tpot")),
        el("td", { class: "muted" }, pp(d, "e2e")),
        el("td", {}, d.state === "ACTIVE" ? [
          el("button", {
            onclick: async () => {
              try {
                await API.postDeploymentsIdScale(
                  d.id, { target: d.target_replicas - 1 });
                pageServing();
              } catch (e) { err.textContent = `scale failed: ${e.message}`; }
            } }, "−"),
          el("button", {
            onclick: async () => {
              try {
                await API.postDeploymentsIdScale(
                  d.id, { target: d.target_replicas + 1 });
                pageServing();
              } catch (e) { err.textContent = `scale failed: ${e.message}`; }
            } }, "+"),
          el("button", {
            onclick: async () => {
              try { await API.postDeploymentsIdKill(d.id); pageServing(); }
              catch (e) { err.textContent = `kill failed: ${e.message}`; }
            } }, "kill"),
        ] : "")))));
  }
  view.append(el("table", {},
    el("tr", {}, ["ID", "State", "Address", "Restarts", "Started", ""]
      .map((h) => el("th", {}, h))),
    serving.map((t) => el("tr", {},
      el("td", {}, el("a", { href: `#/tasks/${t.id}` }, t.id)),
      el("td", {}, t.draining
        ? stateBadge("DRAINING")
        : stateBadge(
          ["COMPLETED", "ERROR", "CANCELED"].includes(t.state)
            ? t.state : (t.allocation_state ?? t.state))),
      el("td", { class: "muted" }, t.proxy_address ?? ""),
      el("td", {}, t.restarts ?? 0),
      el("td", { class: "muted" }, t.start_time ?? ""),
      el("td", {}, !["COMPLETED", "ERROR", "CANCELED"].includes(t.state)
        ? el("button", {
          onclick: async () => {
            try { await API.postServingIdKill(t.id); pageServing(); }
            catch (e) { err.textContent = `kill failed: ${e.message}`; }
          } }, "kill") : "")))));
  if (!serving.length) {
    view.append(el("p", { class: "muted" },
      "no serving tasks — launch one with `det serve <config>`"));
  }
  view.append(err);
}

async function pageDeployment(id) {
  // Deployment detail (docs/serving.md "Request latency & SLOs"):
  // aggregated TTFT/TPOT/e2e/queue-wait percentiles, per-replica health,
  // and the slow-request ring — request ids there feed
  // `det serve trace <deployment> <request-id>`.
  const { deployment: d } = await API.getDeploymentsId(id);
  view.textContent = "";
  view.append(el("h1", {}, `Deployment ${d.name || d.id}`));
  view.append(el("p", { class: "muted" },
    `${d.id} — target ${d.target_replicas} in ` +
    `[${d.min_replicas}, ${d.max_replicas}], load ` +
    `${(d.smoothed_load ?? 0).toFixed(2)}` +
    (d.slo_ms ? `, SLO ${d.slo_ms} ms` : "")));
  // Model lifecycle (docs/serving.md "Model lifecycle"): served version,
  // rolling-swap progress, and the canary split.
  view.append(el("p", {},
    el("b", {}, "Version: "), d.model_version ?? "",
    d.swap ? el("span", { class: "muted" },
      `  (rolling from ${d.swap.from || "(initial)"}, ` +
      `${d.swap.replicas_swapped} replica(s) swapped)`) : ""));
  if (d.canary) {
    view.append(el("p", {},
      el("b", {}, "Canary: "),
      `${d.canary.version} at ${d.canary.fraction} of traffic — ` +
      `${d.canary.routed} canary / ${d.canary.routed_stable} stable ` +
      `(observed ${(d.canary.observed_fraction ?? 0).toFixed(3)})`));
  }
  // Canary-vs-stable p50/p99 side by side, one row per served version.
  const byv = d.latency_by_version || {};
  if (Object.keys(byv).length > 1) {
    view.append(el("h2", {}, "Latency by version"));
    view.append(el("table", {},
      el("tr", {}, ["Version", "TTFT p50/p99", "TPOT p50/p99",
        "e2e p50/p99", "requests"].map((h) => el("th", {}, h))),
      Object.entries(byv).map(([version, lat]) => {
        const pp = (key) => {
          const h = lat[key] || {};
          return h.count
            ? `${h.p50_ms.toFixed(0)}/${h.p99_ms.toFixed(0)}` : "—";
        };
        return el("tr", {},
          el("td", {}, version),
          el("td", { class: "muted" }, pp("ttft")),
          el("td", { class: "muted" }, pp("tpot")),
          el("td", { class: "muted" }, pp("e2e")),
          el("td", { class: "muted" }, (lat.e2e || {}).count ?? 0));
      })));
  }
  const lat = d.latency || {};
  view.append(el("h2", {}, "Request latency"));
  view.append(el("table", {},
    el("tr", {}, ["Phase", "p50 ms", "p99 ms", "mean ms", "requests"]
      .map((h) => el("th", {}, h))),
    [["TTFT", "ttft"], ["TPOT (inter-token)", "tpot"], ["End-to-end", "e2e"],
      ["Queue wait", "queue_wait"]].map(([label, key]) => {
      const h = lat[key] || {};
      return el("tr", {},
        el("td", {}, label),
        el("td", {}, h.count ? h.p50_ms.toFixed(1) : "—"),
        el("td", {}, h.count ? h.p99_ms.toFixed(1) : "—"),
        el("td", { class: "muted" },
          h.mean_ms != null ? h.mean_ms.toFixed(1) : "—"),
        el("td", { class: "muted" }, h.count ?? 0));
    })));
  view.append(el("h2", {}, "Replicas"));
  view.append(el("table", {},
    el("tr", {}, ["Task", "State", "Version", "Queue", "Active",
      "e2e p50/p99", "Report age", ""].map((h) => el("th", {}, h))),
    (d.replicas || []).map((r) => {
      const e2e = (r.latency || {}).e2e || {};
      return el("tr", {},
        el("td", {}, el("a", { href: `#/tasks/${r.task_id}` }, r.task_id)),
        el("td", {}, stateBadge(
          r.retiring ? "RETIRING" : r.draining ? "DRAINING"
            : (r.allocation_state ?? "PENDING"))),
        el("td", { class: "muted" },
          (r.model_version || "").replace("checkpoint:", "ckpt:") +
          (r.canary ? " (canary)" : "")),
        el("td", { class: "muted" },
          `${r.queue_depth}/${r.queue_capacity}`),
        el("td", { class: "muted" }, `${r.active}/${r.slots}`),
        el("td", { class: "muted" }, e2e.count
          ? `${e2e.p50_ms.toFixed(0)}/${e2e.p99_ms.toFixed(0)}` : "—"),
        el("td", { class: "muted" },
          r.report_age_s >= 0 ? `${r.report_age_s.toFixed(1)}s` : "never"),
        el("td", { class: "muted" }, r.breaker_open ? "ejected" : ""));
    })));
  view.append(el("h2", {}, "Slow requests"));
  if ((d.slow_requests || []).length) {
    view.append(el("table", {},
      el("tr", {}, ["Request", "ms", "Replica", "Status"]
        .map((h) => el("th", {}, h))),
      d.slow_requests.map((s) => el("tr", {},
        el("td", {}, s.request_id),
        el("td", {}, (s.ms ?? 0).toFixed(1)),
        el("td", { class: "muted" }, s.replica),
        el("td", { class: "muted" }, s.status)))));
    view.append(el("p", { class: "muted" },
      "inspect one with `det serve trace " + d.id + " <request-id>`"));
  } else {
    view.append(el("p", { class: "muted" }, d.slo_ms
      ? "no requests over the SLO"
      : "set serving.slo_ms to record SLO-breaching requests here"));
  }
}

async function pageTaskLogs(id) {
  view.textContent = "";
  view.append(el("h1", {}, `Task ${id}`));
  const pre = el("pre", { class: "logpane" });
  view.append(pre);
  const myGen = gen;
  let offset = 0;
  while (myGen === gen) {
    const { logs } = await API.getTasksIdLogs(
      id, { offset, follow: true, timeout_seconds: 20 });
    if (myGen !== gen) return;
    for (const line of logs) {
      offset = Math.max(offset, line.id);
      pre.append(line.log + "\n");
    }
  }
}

async function pageAdmin() {
  const [{ webhooks }, { templates }] = await Promise.all([
    API.getWebhooks(), API.getTemplates()]);
  view.textContent = "";
  view.append(el("h1", {}, "Admin"));
  const err = el("div", { class: "error" });

  view.append(el("h2", {}, "Webhooks"));
  view.append(el("table", {},
    el("tr", {}, ["ID", "URL", "Triggers", ""].map((h) => el("th", {}, h))),
    (webhooks ?? []).map((w) => el("tr", {},
      el("td", {}, w.id),
      el("td", { class: "muted" }, w.url),
      el("td", {}, (w.triggers ?? []).map(
        (t) => t.trigger_type ?? t).join(", ")),
      el("td", {}, el("button", {
        onclick: async () => {
          try { await API.deleteWebhooksId(w.id); pageAdmin(); }
          catch (e) { err.textContent = String(e.message); }
        } }, "delete"))))));
  const whUrl = el("input", { placeholder: "https://hook.example/path" });
  view.append(el("div", {}, whUrl, el("button", {
    onclick: async () => {
      try {
        await API.postWebhooks({
          url: whUrl.value,
          triggers: [{ trigger_type: "EXPERIMENT_STATE_CHANGE",
                       condition: { state: "COMPLETED" } }] });
        pageAdmin();
      } catch (e) { err.textContent = String(e.message); }
    } }, "add webhook (COMPLETED)")));

  view.append(el("h2", {}, "Templates"));
  view.append(el("table", {},
    el("tr", {}, ["Name", "Config", ""].map((h) => el("th", {}, h))),
    (templates ?? []).map((t) => el("tr", {},
      el("td", {}, t.name),
      el("td", {}, el("pre", { class: "config" },
        JSON.stringify(t.config ?? {}, null, 1))),
      el("td", {}, el("button", {
        onclick: async () => {
          try {
            await API.deleteTemplatesName(encodeURIComponent(t.name));
            pageAdmin();
          }
          catch (e) { err.textContent = String(e.message); }
        } }, "delete"))))));
  const tplName = el("input", { placeholder: "template name" });
  const tplCfg = el("input", {
    placeholder: '{"resources": {"slots_per_trial": 4}}' });
  view.append(el("div", {}, tplName, tplCfg, el("button", {
    onclick: async () => {
      try {
        await API.postTemplates({ name: tplName.value,
                                  config: JSON.parse(tplCfg.value) });
        pageAdmin();
      } catch (e) { err.textContent = String(e.message); }
    } }, "add template")));
  view.append(err);
}

// --------------------------------------------------------------- router

async function route() {
  gen += 1;  // cancels the previous page's stream/log followers
  document.getElementById("whoami").textContent =
    localStorage.getItem("det_user") || "";
  const hash = location.hash || "#/experiments";
  document.querySelectorAll("#topbar a").forEach((a) =>
    a.classList.toggle("active", hash.startsWith(a.getAttribute("href"))));
  try {
    const m = hash.match(/^#\/experiments\/(\d+)/);
    if (m) {
      await pageExperiment(m[1]);
      // Live refresh: any experiment/trial/metric event for this
      // experiment re-renders (throttled by the long-poll itself).
      const myGen = gen;
      followStream("experiments,trials,metrics", (events) => {
        if (myGen !== gen) return;
        if (events === null ||
            events.some((e) =>
              String(e.payload?.id) === m[1] ||
              String(e.payload?.experiment_id) === m[1] ||
              e.entity === "metrics")) {
          pageExperiment(m[1]);
        }
      });
      return;
    }
    const t = hash.match(/^#\/trials\/(\d+)/);
    if (t) return await pageTrial(t[1]);
    const tk = hash.match(/^#\/tasks\/([\w\-]+)/);
    if (tk) return await pageTaskLogs(tk[1]);
    if (hash.startsWith("#/tasks")) return await pageTasks();
    const dp = hash.match(/^#\/serving\/(deploy-[\w\-]+)/);
    if (dp) return await pageDeployment(dp[1]);
    if (hash.startsWith("#/serving")) return await pageServing();
    if (hash.startsWith("#/admin")) return await pageAdmin();
    if (hash.startsWith("#/workspaces")) return await pageWorkspaces();
    if (hash.startsWith("#/models")) return await pageModels();
    if (hash.startsWith("#/users")) return await pageUsers();
    if (hash.startsWith("#/cluster")) return await pageCluster();
    if (hash.startsWith("#/jobs")) return await pageJobs();
    await pageExperiments();
    {
      // Experiment list stays live without reload via /api/v1/stream.
      const myGen = gen;
      followStream("experiments", () => {
        if (myGen === gen) pageExperiments();
      });
    }
  } catch (e) {
    if (e.message !== "unauthenticated") {
      view.textContent = "";
      view.append(el("p", { class: "error" }, String(e)));
    }
  }
}

window.addEventListener("hashchange", route);
if (!token()) renderLogin();
else route();
