#!/usr/bin/env python
"""Headline benchmark: GPT-2 (124M) pretraining throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no training-throughput numbers (BASELINE.md), so
`vs_baseline` is measured MFU relative to the driver's 40% MFU target
(BASELINE.json north star): vs_baseline = MFU / 0.40. >1.0 beats the target.

Config: GPT-2 small, bf16, remat, seq 1024, per-chip batch 16 — the
single-chip unit of the v5e-64 GPT-2 north-star workload.
"""

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax
    import optax

    from determined_tpu.models import gpt2
    from determined_tpu.train import create_train_state, make_train_step

    from determined_tpu.train import make_multi_step

    # scan_unroll=0: fully unroll the layer scan — worth ~3 MFU points on
    # v5e (removes stacked-param dynamic-slices + scan-carry stacking).
    cfg = gpt2.Config(scan_unroll=0)
    B, S = 16, 1024
    # N optimizer steps per dispatch (lax.scan in one jit): amortizes the
    # host→device dispatch + sync latency exactly the way the Trainer's
    # production loop does. Essential under remote-tunnel PJRT backends
    # where a round trip costs ~100 ms.
    STEPS_PER_CALL = 10
    peak_flops = _peak_flops()

    tx = optax.adamw(3e-4)
    state = create_train_state(lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0))
    step = make_multi_step(
        lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx, STEPS_PER_CALL
    )
    batches = {
        "tokens": np.random.default_rng(0)
        .integers(0, cfg.vocab_size, size=(STEPS_PER_CALL, B, S + 1))
        .astype(np.int32)
    }

    # warmup / compile
    state, m = step(state, batches, jax.random.PRNGKey(0))
    float(m["loss"])  # full sync (block_until_ready is a no-op on some PJRT backends)

    n_calls = 3
    t0 = time.time()
    for i in range(n_calls):
        state, m = step(state, batches, jax.random.PRNGKey(100 + i))
    float(m["loss"])
    dt = (time.time() - t0) / (n_calls * STEPS_PER_CALL)

    tokens_per_sec = B * S / dt
    samples_per_sec = B / dt
    mfu = gpt2.flops_per_token(cfg, S) * tokens_per_sec / peak_flops

    print(
        json.dumps(
            {
                "metric": "gpt2_124m_samples_per_sec_per_chip",
                "value": round(samples_per_sec, 2),
                "unit": "samples/sec/chip (seq=1024)",
                "vs_baseline": round(mfu / 0.40, 3),
                "detail": {
                    "tokens_per_sec": round(tokens_per_sec),
                    "step_ms": round(dt * 1000, 1),
                    "mfu": round(mfu, 4),
                    "batch": B,
                    "seq": S,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def _peak_flops() -> float:
    """bf16 peak of the bench chip; v5e ≈ 197 TFLOP/s."""
    return 197e12


if __name__ == "__main__":
    sys.exit(main())
