#!/usr/bin/env python
"""BASELINE.md benchmarks. Headline: GPT-2 (124M) pretraining throughput on
one TPU chip.

Prints one JSON line PER METRIC (gpt2 first — the headline — then
resnet50 samples/sec/chip and asha trials/hour, so every BASELINE.md
metric lands in BENCH_r{N}.json):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no training-throughput numbers (BASELINE.md), so
`vs_baseline` is measured MFU relative to the driver's 40% MFU target
(BASELINE.json north star): vs_baseline = MFU / 0.40. >1.0 beats the target.

GPT-2 config: small, bf16, remat, seq 1024, per-chip batch 16 — the
single-chip unit of the v5e-64 GPT-2 north-star workload.

`--only gpt2|resnet|asha` runs a single section; a failing section prints
an error line and the others still run.
"""

import json
import sys
import time

import numpy as np


def gpt2_bench() -> None:
    import jax
    import optax

    from determined_tpu.models import gpt2
    from determined_tpu.train import create_train_state, make_train_step

    from determined_tpu.train import make_multi_step

    # scan_unroll=0: fully unroll the layer scan — worth ~3 MFU points on
    # v5e (removes stacked-param dynamic-slices + scan-carry stacking).
    # remat=False: at 124M/B16/S1024 activations fit HBM comfortably, and
    # skipping the recompute is worth ~5 MFU points (measured 43.7% → 48.9%
    # on the bench chip; larger configs on real pods re-enable remat).
    cfg = gpt2.Config(scan_unroll=0, remat=False)
    B, S = 16, 1024
    # N optimizer steps per dispatch (lax.scan in one jit): amortizes the
    # host→device dispatch + sync latency exactly the way the Trainer's
    # production loop does. Essential under remote-tunnel PJRT backends
    # where a round trip costs ~100 ms.
    STEPS_PER_CALL = 10
    peak_flops = _peak_flops()

    tx = optax.adamw(3e-4)
    state = create_train_state(lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0))
    step = make_multi_step(
        lambda p, b, r: gpt2.loss_fn(p, b, cfg), tx, STEPS_PER_CALL
    )
    batches = {
        "tokens": np.random.default_rng(0)
        .integers(0, cfg.vocab_size, size=(STEPS_PER_CALL, B, S + 1))
        .astype(np.int32)
    }

    # warmup / compile
    state, m = step(state, batches, jax.random.PRNGKey(0))
    float(m["loss"])  # full sync (block_until_ready is a no-op on some PJRT backends)

    n_calls = 3
    t0 = time.time()
    for i in range(n_calls):
        state, m = step(state, batches, jax.random.PRNGKey(100 + i))
    float(m["loss"])
    dt = (time.time() - t0) / (n_calls * STEPS_PER_CALL)

    tokens_per_sec = B * S / dt
    samples_per_sec = B / dt
    mfu = gpt2.flops_per_token(cfg, S) * tokens_per_sec / peak_flops

    print(
        json.dumps(
            {
                "metric": "gpt2_124m_samples_per_sec_per_chip",
                "value": round(samples_per_sec, 2),
                "unit": "samples/sec/chip (seq=1024)",
                "vs_baseline": round(mfu / 0.40, 3),
                "detail": {
                    "tokens_per_sec": round(tokens_per_sec),
                    "step_ms": round(dt * 1000, 1),
                    "mfu": round(mfu, 4),
                    "batch": B,
                    "seq": S,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


def _peak_flops() -> float:
    """bf16 peak of the bench chip; v5e ≈ 197 TFLOP/s."""
    return 197e12


def train_attn_bench() -> None:
    """`make bench-train` (docs/training-perf.md): the four-leg training-
    attention A/B — dense → flash(f32) → flash(bf16) → flash+overlap.

    Two tiers, mirroring bench-compile's measured+modeled split:

    (a) MEASURED — all four legs run interleaved on THIS machine's mesh
        (same devices, same init, same batches; only the `optimizations`
        knob changes): per-leg step_ms and one-step loss, gating the
        numerics contract (flash ≡ dense arithmetic; bf16 within
        tolerance). Caveat, printed in the JSON: on a CPU bench host the
        pallas legs execute in *interpret mode* (the correctness path
        tier-1 uses), so CPU step_ms for flash legs measures the
        interpreter, not the kernel — the wiring and numerics are what
        the measured tier gates there.

    (b) MODELED — a v5e roofline for the full-size workload (gpt2-124M,
        seq 1024, per-chip batch 16), anchored to the recorded 50.5%-MFU
        dense baseline: the model only *differences* the attention and
        comm terms each leg changes (full-vs-causal FLOPs, fp32-vs-bf16
        MXU rate on the probability matmuls, materialized-score HBM
        traffic, exposed all-gather time), with every constant stated in
        the output. Gate: modeled step_ms strictly improves per leg and
        the final leg's MFU >= 55%.
    """
    import os

    if "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import optax

    from determined_tpu.models import gpt2
    from determined_tpu.parallel.mesh import AXIS_ORDER, MeshConfig
    from determined_tpu.parallel.sharding import LogicalRules
    from determined_tpu.train import create_train_state, make_train_step

    # Small enough to finish under interpret-mode pallas on CPU, but with a
    # pallas-supported geometry (seq % 128 == 0, head dim 64).
    B, S = 8, 128
    n_dev = len(jax.devices())
    fsdp = n_dev if n_dev in (2, 4, 8) else 1
    shape = MeshConfig(data=1, fsdp=fsdp).resolve(fsdp).sizes()
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:fsdp]).reshape(shape), AXIS_ORDER)
    rules = LogicalRules()

    def leg_cfg(impl, bf16=False, overlap=False):
        return gpt2.Config(
            vocab_size=512, n_positions=S, d_model=256, n_layer=2, n_head=4,
            remat=False, attention_impl=impl, attention_bf16=bf16,
            overlap_allgather=overlap)

    legs = [
        ("dense", leg_cfg("dense")),
        ("flash_f32", leg_cfg("pallas")),
        ("flash_bf16", leg_cfg("pallas", bf16=True)),
        ("flash_bf16_overlap", leg_cfg("pallas", bf16=True, overlap=True)),
    ]
    batch = {"tokens": np.random.default_rng(0).integers(
        0, 512, size=(B, S + 1)).astype(np.int32)}

    def run_leg(cfg):
        tx = optax.adamw(3e-4)
        with mesh:
            state = create_train_state(
                lambda r: gpt2.init(r, cfg), tx, jax.random.PRNGKey(0))
            step = make_train_step(
                lambda p, b, r: gpt2.loss_fn(p, b, cfg, rules), tx,
                mesh=mesh, rules=rules)
            state, m = step(state, batch, jax.random.PRNGKey(1))  # compile
            first_loss = float(m["loss"])
            n_calls = 3
            t0 = time.time()
            for i in range(n_calls):
                state, m = step(state, batch, jax.random.PRNGKey(2 + i))
            float(m["loss"])
            return (time.time() - t0) / n_calls * 1e3, first_loss

    # Interleave two full rounds and keep each leg's best pass so process
    # warmup (allocator, caches) doesn't bias whichever leg runs first.
    measured = {name: {"step_ms": float("inf"), "loss": None}
                for name, _ in legs}
    for _ in range(2):
        for name, cfg in legs:
            ms, loss = run_leg(cfg)
            if ms < measured[name]["step_ms"]:
                measured[name] = {"step_ms": round(ms, 1),
                                  "loss": round(loss, 4)}

    d_loss = measured["dense"]["loss"]
    f32_delta = abs(measured["flash_f32"]["loss"] - d_loss)
    bf16_delta = abs(measured["flash_bf16"]["loss"] - d_loss)
    backend = jax.default_backend()
    print(json.dumps({
        "metric": "train_attn_loss_parity",
        "value": round(f32_delta, 5),
        "unit": "|loss(flash_f32) - loss(dense)| one step, same init/batch "
                "(gate: < 0.05; bf16 leg < 0.1)",
        "vs_baseline": 1.0,
        "detail": {
            "legs": measured,
            "bf16_delta": round(bf16_delta, 5),
            "mesh": dict(zip(AXIS_ORDER, shape)),
            "backend": backend,
            "caveat": (None if backend in ("tpu", "axon") else
                       "CPU host: pallas legs run in interpret mode, so "
                       "their step_ms measures the interpreter — numerics "
                       "and wiring are the gates here; kernel-speed gates "
                       "live in the modeled tier below"),
        },
    }))
    assert f32_delta < 0.05, measured
    assert bf16_delta < 0.10, measured
    assert (abs(measured["flash_bf16_overlap"]["loss"]
                - measured["flash_bf16"]["loss"]) < 0.05), measured

    # ---- (b) v5e roofline, anchored to the 50.5% dense baseline --------
    PEAK = 197e12          # v5e bf16 MXU peak, FLOP/s
    FP32_RATE = PEAK / 4   # fp32 matmul throughput on the same MXU
    HBM_BW = 819e9         # v5e HBM bandwidth, B/s
    AG_BW = 9e10           # effective per-chip fsdp all-gather BW, B/s
    EXPOSED = 0.6          # fraction of all-gather time XLA fails to hide
    SCORE_PASSES = 8       # fp32 HBM passes over [B,H,S,S] scores (dense
    #                        fwd write+read, probs write+read, bwd x4)
    BASE_MFU = 0.505       # the recorded dense-path baseline (BENCH_r*)

    mcfg = gpt2.Config()   # gpt2-124M, the north-star per-chip workload
    MB, MS = 16, 1024
    tokens = MB * MS
    useful = gpt2.flops_per_token(mcfg, MS) * tokens
    L, D, H = mcfg.n_layer, mcfg.d_model, mcfg.n_head

    t_base = 6.0 * gpt2.param_count(mcfg) * tokens / PEAK
    attn_causal = 6.0 * L * D * MS * tokens   # fwd+bwd causal matmul FLOPs
    attn_full = 2.0 * attn_causal             # dense computes the full S^2
    t_scores = SCORE_PASSES * MB * H * MS * MS * 4 / HBM_BW
    layer_bytes = (gpt2.param_count(mcfg) / L) * 2  # bf16 layer params
    t_ag = 3 * L * layer_bytes / AG_BW        # fwd + bwd re-gather + RS

    def leg_time(attn_s, comm_s):
        return t_base + attn_s + comm_s + t_other

    t_dense_attn = attn_full / PEAK + t_scores
    # Calibrate the residual (remat recompute, layernorms, host gaps, ...)
    # so the dense leg reproduces the recorded baseline exactly; every
    # other leg reuses it — the model only differences what each leg
    # changes.
    t_other = (useful / (BASE_MFU * PEAK)
               - (t_base + t_dense_attn + EXPOSED * t_ag))

    modeled = {}
    for name, attn_s, comm_s in [
        ("dense", t_dense_attn, EXPOSED * t_ag),
        # flash f32: causal-only FLOPs, no score traffic; the P-side
        # matmuls (half the attention FLOPs) run at the fp32 MXU rate.
        ("flash_f32",
         0.5 * attn_causal / PEAK + 0.5 * attn_causal / FP32_RATE,
         EXPOSED * t_ag),
        ("flash_bf16", attn_causal / PEAK, EXPOSED * t_ag),
        # overlap: the one-layer-ahead prefetch hides the gather behind
        # the previous layer's compute; ~5% residual exposure remains.
        ("flash_bf16_overlap", attn_causal / PEAK, 0.05 * t_ag),
    ]:
        t = leg_time(attn_s, comm_s)
        modeled[name] = {"step_ms": round(t * 1e3, 1),
                         "mfu": round(useful / (t * PEAK), 4)}

    final = modeled["flash_bf16_overlap"]["mfu"]
    print(json.dumps({
        "metric": "train_attn_modeled_mfu",
        "value": final,
        "unit": "modeled MFU, gpt2-124M seq=1024 B=16/chip on v5e "
                "(dense baseline calibrated to the recorded 50.5%; "
                "gate: >= 0.55, step_ms strictly improving per leg)",
        "vs_baseline": round(final / BASE_MFU, 3),
        "detail": {
            "legs": modeled,
            "assumptions": {
                "peak_bf16_flops": PEAK, "fp32_matmul_flops": FP32_RATE,
                "hbm_bw": HBM_BW, "allgather_bw": AG_BW,
                "exposed_ag_fraction": EXPOSED,
                "score_hbm_passes": SCORE_PASSES,
                "calibrated_other_ms": round(t_other * 1e3, 1),
            },
        },
    }))
    ms_seq = [modeled[n]["step_ms"] for n, _ in legs]
    assert all(a > b for a, b in zip(ms_seq, ms_seq[1:])), modeled
    assert final >= 0.55, modeled


def input_pipeline_bench() -> None:
    """Async input pipeline A/B (`make bench-input`): the same slow-host
    loader + fixed-cost step, synchronous vs DevicePrefetcher. Reports the
    steady-state step-time speedup and the input_wait_ms collapse — the
    ISSUE-3 acceptance numbers, measured on this machine."""
    from determined_tpu.data.bench import ab_compare

    host_delay_s, step_s, n = 0.020, 0.050, 20

    def make_iter():
        rng = np.random.default_rng(0)
        def gen():
            for _ in range(n):
                time.sleep(host_delay_s)  # simulated host preprocessing
                yield {"x": rng.normal(size=(64, 256)).astype(np.float32)}
        return gen()

    def step_fn(batch):
        time.sleep(step_s)  # stands in for dispatched device compute

    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = jax.devices()
    sharding = NamedSharding(
        Mesh(np.asarray(devs[:1]).reshape(1), ("data",)),
        PartitionSpec("data"))
    result = ab_compare(make_iter, step_fn, sharding=sharding, depth=2)
    print(json.dumps({
        "metric": "input_pipeline_speedup",
        "value": result["speedup"],
        "unit": "x vs synchronous feed (20ms host, 50ms step)",
        "vs_baseline": result["speedup"],  # sync feed IS the baseline
        "detail": {
            "sync_step_ms": result["sync"]["step_ms"],
            "prefetch_step_ms": result["prefetch"]["step_ms"],
            "sync_input_wait_ms": result["sync"]["input_wait_ms"],
            "prefetch_input_wait_ms": result["prefetch"]["input_wait_ms"],
            "input_wait_ms_delta": result["input_wait_ms_delta"],
            "h2d_ms": result["prefetch"].get("h2d_ms"),
            "depth": result["depth"],
        },
    }))


def elastic_bench() -> None:
    """`make bench-elastic`: resize downtime (signal -> first post-resize
    step) vs the restart-from-checkpoint requeue baseline, same drain
    scenario (docs/elasticity.md).

    Both paths take the same deadline-budgeted emergency checkpoint and
    end up training at the target size. The resize path reshards in
    process (abstract restore template, one retrace). The baseline pays
    what a PR-5 requeue actually pays: a FRESH task process (python + jax
    + orbax import, device init), full Trainer build at the target size,
    restore, recompile — measured by really spawning one. It is still
    CONSERVATIVE: a real requeue also waits in the scheduler queue, which
    is unbounded and excluded here. Resize must win even against the
    zero-queue-wait requeue."""
    import os
    import subprocess
    import tempfile
    import textwrap

    if "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=4")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from determined_tpu import _jax_compat, core
    from determined_tpu.train import Trainer
    from determined_tpu.train.trial import JaxTrial, TrialContext
    from determined_tpu.parallel.mesh import MeshConfig

    _jax_compat.install()
    import optax

    devices = jax.devices()
    src = min(4, len(devices))
    tgt = max(1, src // 2)
    dim, resize_at, total = 256, 8, 16

    class Elastic(JaxTrial):
        prefetch = False

        def __init__(self, ctx, start=0, action=None):
            super().__init__(ctx)
            self._start, self._action = start, action

        def init_params(self, rng):
            return {"w": jax.random.normal(rng, (dim, dim)) * 0.02}

        def param_logical_axes(self):
            return {"w": (None, None)}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((batch["x"] @ params["w"]) ** 2)

        def optimizer(self):
            return optax.sgd(0.01)

        def mesh_config(self):
            return MeshConfig()

        def build_training_data(self):
            for i in range(self._start, 4096):
                if self._action is not None and i == resize_at:
                    self._action()
                rng = np.random.default_rng(100 + i)
                yield {"x": rng.normal(size=(8, dim)).astype(np.float32)}

    def timed_reports(ctx):
        """Wall timestamp per training report (report_period=1 => per
        step) — the 'first post-resize step' instant without touching the
        hot loop."""
        stamps = []
        orig = ctx.train.report_training_metrics

        def wrapped(steps_completed, metrics, **kw):
            stamps.append((time.monotonic(), steps_completed, dict(metrics)))
            return orig(steps_completed, metrics, **kw)

        ctx.train.report_training_metrics = wrapped
        return stamps

    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    signal_t = {}

    # Warmup: the first orbax save/restore in a process pays one-time
    # import/registry setup (~300ms) — absorb it here so neither measured
    # path carries it.
    ctx = core.init(max_length=2, checkpoint_dir=tmp + "/warm",
                    async_checkpointing=False)
    trainer = Trainer(Elastic(TrialContext()), core_context=ctx,
                      devices=devices[:src])
    trainer.fit(report_period=1, checkpoint_period=1)
    trainer._restore("trial0-step2")
    ctx.close()

    # --- resize path: in-process reshard, same allocation semantics.
    ctx = core.init(max_length=total, checkpoint_dir=tmp + "/a",
                    async_checkpointing=False)
    stamps = timed_reports(ctx)

    def fire():
        signal_t["t"] = time.monotonic()
        ctx.preempt.force_resize(tgt, deadline=60.0)

    trainer = Trainer(Elastic(TrialContext(), action=fire),
                      core_context=ctx, devices=devices[:src])
    trainer.fit(report_period=1, preempt_period=1)
    assert trainer.mesh.size == tgt
    resize_step = next(s for _, s, m in stamps if "resize_downtime_ms" in m)
    first_after = next(t for t, s, m in stamps
                       if s > resize_step and "loss" in m)
    resize_downtime_s = first_after - signal_t["t"]
    ctx.close()

    # --- requeue baseline: emergency checkpoint + a FRESH task process
    # restoring at the target size (what restart-from-checkpoint costs
    # with zero queue wait). CLOCK_MONOTONIC is machine-wide on Linux, so
    # the child's first-step stamp is directly comparable.
    ctx = core.init(max_length=resize_at + 1, checkpoint_dir=tmp + "/b",
                    async_checkpointing=False)

    def fire2():
        signal_t["t"] = time.monotonic()
        ctx.preempt.force(deadline=60.0)

    trainer = Trainer(Elastic(TrialContext(), action=fire2),
                      core_context=ctx, devices=devices[:src])
    state = trainer.fit(report_period=1, preempt_period=1)
    step = int(jax.device_get(state.step))
    ctx.close()  # the preempted container exits here

    child = os.path.join(tmp, "requeue_child.py")
    with open(child, "w") as f:
        f.write(textwrap.dedent(f"""
            import os, sys, time
            os.environ["XLA_FLAGS"] = (
                " --xla_force_host_platform_device_count={tgt}")
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax, numpy as np, optax
            from determined_tpu import _jax_compat, core
            _jax_compat.install()
            from determined_tpu.train import Trainer
            from determined_tpu.train.trial import JaxTrial, TrialContext
            from determined_tpu.parallel.mesh import MeshConfig

            dim, start, total = {dim}, {step}, {total}

            class Elastic(JaxTrial):
                prefetch = False
                def init_params(self, rng):
                    return {{"w": jax.random.normal(rng, (dim, dim)) * 0.02}}
                def param_logical_axes(self):
                    return {{"w": (None, None)}}
                def loss(self, params, batch, rng):
                    import jax.numpy as jnp
                    return jnp.mean((batch["x"] @ params["w"]) ** 2)
                def optimizer(self):
                    return optax.sgd(0.01)
                def mesh_config(self):
                    return MeshConfig()
                def build_training_data(self):
                    for i in range(start, 4096):
                        rng = np.random.default_rng(100 + i)
                        yield {{"x": rng.normal(size=(8, dim))
                               .astype(np.float32)}}

            ctx = core.init(max_length=total,
                            checkpoint_dir={tmp + "/b"!r},
                            async_checkpointing=False)
            orig = ctx.train.report_training_metrics
            done = []
            def wrapped(steps_completed, metrics, **kw):
                if "loss" in metrics and not done:
                    done.append(1)
                    print("FIRST_STEP", time.monotonic(), flush=True)
                return orig(steps_completed, metrics, **kw)
            ctx.train.report_training_metrics = wrapped
            trainer = Trainer(Elastic(TrialContext()), core_context=ctx,
                              devices=jax.devices())
            trainer.fit(report_period=1,
                        resume_from="trial0-step" + str(start))
            ctx.close()
        """))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, child], env=env,
                          capture_output=True, text=True, timeout=600)
    first_after = None
    for line in proc.stdout.splitlines():
        if line.startswith("FIRST_STEP"):
            first_after = float(line.split()[1])
    assert first_after is not None, proc.stdout + proc.stderr
    requeue_baseline_s = first_after - signal_t["t"]

    print(json.dumps({
        "metric": "elastic_resize_downtime_s",
        "value": round(resize_downtime_s, 3),
        "unit": f"s signal->first step after {src}->{tgt} slot resize",
        "vs_baseline": round(requeue_baseline_s / resize_downtime_s, 2),
        "detail": {
            "requeue_baseline_s": round(requeue_baseline_s, 3),
            "resize_beats_requeue": resize_downtime_s < requeue_baseline_s,
            "src_slots": src,
            "target_slots": tgt,
            "note": "baseline spawns a real fresh task process (restore + "
                    "recompile) but excludes scheduler queue wait, which "
                    "is unbounded in a real requeue",
        },
    }))


def compile_bench() -> None:
    """`make bench-compile` (docs/compile-farm.md): the compile-farm A/B on
    a real devcluster — nocache vs persistent-XLA-cache vs farm arms of
    sequential compile-bound GPT-2 trials. Headline:
    `cached_median_compile_s` (farm-arm warm trials; the acceptance gate is
    <= 0.5s, down from ~5.2s with the persistent cache alone in BENCH_r05)
    plus the farm on/off trials/hour delta."""
    import os
    import subprocess
    import tempfile

    REPO = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                   check=True, capture_output=True)
    from bench_asha import run_compile_farm
    from tests.test_platform_e2e import Devcluster

    tmp = tempfile.mkdtemp(prefix="bench_compile_")
    cluster = Devcluster(tmp, os.path.join(REPO, "native", "bin"), slots=1)
    try:
        cluster.start_master()
        cluster.start_agent()
        token = cluster.login()
        detail = run_compile_farm(cluster, token, tmp)
    finally:
        cluster.stop()
    cached = detail.get("cached_median_compile_s")
    print(json.dumps({
        "metric": "cached_median_compile_s",
        "value": cached,
        "unit": "s (median first-step cost of warm farm trials)",
        # The gate: recompilation eliminated as a per-trial cost.
        "vs_baseline": round(0.5 / cached, 2) if cached else None,
        "detail": detail,
    }))
    assert cached is not None and cached <= 0.5, (
        f"cached_median_compile_s {cached} exceeds the 0.5s gate "
        f"({detail})")


def trace_bench() -> None:
    """`make bench-trace` (docs/observability.md): (a) step_ms with
    lifecycle tracing on vs off — the <1% overhead gate that keeps
    tracing always-on; (b) span-ingest throughput on the real master
    under concurrent batched POSTs, the `bench_asha.py`-shaped control-
    plane load."""
    import os
    import tempfile
    import threading

    import jax
    import optax

    from determined_tpu import core
    from determined_tpu.parallel.mesh import MeshConfig
    from determined_tpu.train import Trainer
    from determined_tpu.train.trial import JaxTrial, TrialContext

    class TinyTrial(JaxTrial):
        prefetch = False

        def init_params(self, rng):
            return {"w": jax.random.normal(rng, (256, 256)) * 0.02}

        def param_logical_axes(self):
            return {"w": (None, None)}

        def loss(self, params, batch, rng):
            import jax.numpy as jnp

            return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

        def optimizer(self):
            return optax.sgd(1e-3)

        def mesh_config(self):
            return MeshConfig()

        def build_training_data(self):
            rng = np.random.default_rng(0)
            for _ in range(4096):
                yield {"x": rng.normal(size=(32, 256)).astype(np.float32),
                       "y": rng.normal(size=(32, 256)).astype(np.float32)}

    def steady_sps(trace_off: bool):
        """Median steps/second across post-compile metric flushes for one
        local fit (tracing toggled via DET_TRACE_OFF)."""
        old = os.environ.get("DET_TRACE_OFF")
        os.environ["DET_TRACE_OFF"] = "1" if trace_off else "0"
        try:
            with tempfile.TemporaryDirectory() as tmp:
                ctx = core.init(max_length=400, checkpoint_dir=tmp,
                                async_checkpointing=False)
                trainer = Trainer(TinyTrial(TrialContext()),
                                  core_context=ctx)
                trainer.fit(report_period=20, checkpoint_period=100)
                flushes = [m["metrics"]["steps_per_second"]
                           for m in ctx.train.local_training_metrics
                           if "steps_per_second" in m["metrics"]]
                n_spans = len(ctx.tracer.local_spans)
                ctx.close()
            assert len(flushes) >= 5, flushes
            # Drop the compile-bearing first flush; median over the rest.
            return float(np.median(flushes[1:])), n_spans
        finally:
            if old is None:
                os.environ.pop("DET_TRACE_OFF", None)
            else:
                os.environ["DET_TRACE_OFF"] = old

    # Interleave on/off runs in one process AND alternate which goes
    # first each round: process warmup (allocator, caches) favors
    # whichever mode runs later, so a fixed order would bias the delta.
    on_runs, off_runs, spans_per_run = [], [], 0
    for i in range(4):
        for off_first in ([True, False] if i % 2 else [False, True]):
            sps, n_spans = steady_sps(trace_off=off_first)
            (off_runs if off_first else on_runs).append(sps)
            if not off_first:
                spans_per_run = n_spans
    sps_on = float(np.median(on_runs))
    sps_off = float(np.median(off_runs))
    overhead_pct = (sps_off / sps_on - 1.0) * 100.0

    print(json.dumps({
        "metric": "trace_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "% step_ms added by always-on tracing (gate: < 1%)",
        "vs_baseline": round(sps_on / sps_off, 4),
        "detail": {
            "steps_per_s_tracing_on": round(sps_on, 2),
            "steps_per_s_tracing_off": round(sps_off, 2),
            "spans_emitted_per_run": spans_per_run,
            "gate_passed": overhead_pct < 1.0,
        },
    }))

    # (b) span-ingest throughput on the real master.
    import shutil
    import subprocess
    import uuid

    repo = os.path.dirname(os.path.abspath(__file__))
    bindir = os.path.join(repo, "native", "bin")
    if not os.path.exists(os.path.join(bindir, "determined-master")):
        subprocess.run(["make", "-C", os.path.join(repo, "native")],
                       check=True, capture_output=True)
    sys.path.insert(0, repo)
    from tests.test_platform_e2e import Devcluster

    tmp = tempfile.mkdtemp(prefix="bench_trace_")
    cluster = Devcluster(tmp, bindir)
    try:
        cluster.start_master()
        token = cluster.login()
        eid = cluster.api("POST", "/api/v1/experiments",
                          {"unmanaged": True,
                           "config": {"name": "bench-trace"}},
                          token=token)["id"]
        tid = cluster.api("POST", f"/api/v1/experiments/{eid}/trials",
                          {"hparams": {}}, token=token)["id"]

        batch_size, n_threads, batches_per_thread = 100, 4, 25

        def make_batch():
            t0 = int(time.time() * 1e6)
            return {"spans": [
                {"trace_id": "bench", "span_id": uuid.uuid4().hex[:16],
                 "parent": "bench", "name": "harness.validate",
                 "start_us": t0 + i, "end_us": t0 + i + 1000,
                 "attrs": {"bench": True}}
                for i in range(batch_size)]}

        errors = []

        def pump():
            for _ in range(batches_per_thread):
                try:
                    cluster.api("POST", f"/api/v1/trials/{tid}/spans",
                                make_batch(), token=token)
                except Exception as e:  # noqa: BLE001 — report, don't hang
                    errors.append(e)

        threads = [threading.Thread(target=pump) for _ in range(n_threads)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.monotonic() - t0
        assert not errors, errors[0]
        total = batch_size * n_threads * batches_per_thread
        rows = None
        trace = cluster.api("GET", f"/api/v1/trials/{tid}/trace",
                            token=token)
        rows = len(trace["spans"])
        print(json.dumps({
            "metric": "span_ingest_spans_per_s",
            "value": round(total / dt, 1),
            "unit": f"spans/s ({n_threads} writers, {batch_size}/batch, "
                    "persisted + readable)",
            "vs_baseline": 1.0,
            "detail": {
                "total_spans": total,
                "rows_readable": rows,
                "wall_s": round(dt, 3),
                "all_persisted": rows == total,
            },
        }))
    finally:
        cluster.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def serve_bench() -> None:
    """`make bench-serve`: continuous batching vs the sequential
    one-request-at-a-time baseline on the same GPT-2 checkpoint.

    End-to-end through the real serving stack: a checkpoint is written,
    integrity-verified and loaded (engine.load_checkpoint_params), both
    engines AOT-compile, and the SAME 32-request burst (random prompt
    lengths, 32 new tokens each) runs through (a) a 1-slot batcher —
    requests strictly one at a time — and (b) the 8-slot continuous
    batcher. Emits serve_tokens_per_s / serve_p50_ms / serve_p99_ms; the
    ISSUE-6 acceptance bar is tokens/s >= 1.5x sequential.
    """
    import tempfile

    import jax

    from determined_tpu import core
    from determined_tpu.models import gpt2
    from determined_tpu.serve import (
        AdmissionQueue, BlockManager, ContinuousBatcher, Request,
        ServingEngine, load_checkpoint_params)

    # gpt2-small on an accelerator (the flagship config at bench-chip
    # scale); CPU-only environments drop to tiny so the section finishes
    # inside a CI budget. Override either way with DET_BENCH_SERVE_MODEL.
    # The metric's unit string names the model, so rounds stay comparable.
    import os

    import jax as _jd

    default_size = ("small" if _jd.default_backend() in ("tpu", "axon")
                    else "tiny")
    size = os.environ.get("DET_BENCH_SERVE_MODEL", default_size)
    base = {"tiny": gpt2.Config.tiny, "small": gpt2.Config.small}[size]()
    cfg = gpt2.Config(
        vocab_size=base.vocab_size, n_positions=base.n_positions,
        d_model=base.d_model, n_layer=base.n_layer, n_head=base.n_head,
        remat=False, attention_impl="dot")
    slots, n_requests, max_new = 8, 32, 32
    max_seq = min(192, base.n_positions)  # the engine clamps anyway; the
    buckets = [64]                        # A/B HBM math must match it

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size,
                            size=int(rng.integers(8, 49))).astype(np.int32)
               for _ in range(n_requests)]

    # Serve from an actual committed checkpoint: load path included.
    with tempfile.TemporaryDirectory() as td:
        ctx = core.init(max_length=1, checkpoint_dir=td)
        params = gpt2.init(jax.random.PRNGKey(0), cfg)
        import jax.numpy as jnp

        ctx.checkpoint.save_state(
            {"step": jnp.asarray(1, jnp.int32), "params": params,
             "opt_state": {"count": jnp.zeros((), jnp.int32)}}, 1)
        ctx.checkpoint.wait()
        loaded = load_checkpoint_params(ctx.checkpoint, "trial0-step1")
        ctx.close()

    def run(n_slots, tracing=False):
        engine = ServingEngine(
            loaded, cfg, slots=n_slots, max_seq_len=max_seq,
            prefill_buckets=buckets)
        batcher = ContinuousBatcher(
            engine, queue=AdmissionQueue(n_requests),
            block_manager=BlockManager(
                num_blocks=n_slots * (max_seq // 16), block_size=16),
            idle_wait_s=0.002)
        tracer = None
        if tracing:
            # The production request tracer with its shipper thread
            # running (local sink: no master in this bench, the span
            # build + buffer cost is what's being measured).
            from determined_tpu.serve.tracing import RequestTracer

            tracer = RequestTracer(None, "", sample=1.0,
                                   flush_period_s=0.5).start()
            batcher.tracer = tracer
        batcher.start()  # compiles AOT; excluded from the timed window
        try:
            t0 = time.time()
            reqs = [batcher.submit(Request(p, max_new_tokens=max_new))
                    for p in prompts]
            results = [r.result(timeout=1800) for r in reqs]
            wall = time.time() - t0
            lats = sorted(r["latency_ms"] for r in results)
            stats = batcher.stats()
            return {
                "wall_s": wall,
                "tokens_per_s": stats["generated_tokens"] / wall,
                "p50_ms": lats[len(lats) // 2],
                "p99_ms": lats[min(len(lats) - 1,
                                   int(len(lats) * 0.99))],
                "mean_occupancy": stats["mean_occupancy"],
                "compile": engine.compile_stats,
                "latency": stats["latency"],
                "spans_recorded": tracer.recorded if tracer else 0,
            }
        finally:
            batcher.stop()
            if tracer is not None:
                tracer.stop()

    seq = run(1)        # sequential baseline: one slot = no batching
    cont = run(slots)   # continuous batching
    speedup = cont["tokens_per_s"] / seq["tokens_per_s"]

    detail = {
        "model": f"gpt2-{size}",
        "requests": n_requests,
        "max_new_tokens": max_new,
        "slots": slots,
        "mean_occupancy": cont["mean_occupancy"],
        "sequential_tokens_per_s": round(seq["tokens_per_s"], 1),
        "sequential_p50_ms": round(seq["p50_ms"], 1),
        "wall_s": round(cont["wall_s"], 2),
        "compile_total_s": cont["compile"].get("total_s"),
        "device": None,
    }
    import jax as _jax

    detail["device"] = str(_jax.devices()[0])
    print(json.dumps({
        "metric": "serve_tokens_per_s",
        "value": round(cont["tokens_per_s"], 1),
        "unit": f"tokens/s (gpt2-{size}, {n_requests}-burst x {max_new} "
                f"new tokens, {slots} slots)",
        "vs_baseline": round(speedup, 3),  # sequential feed IS the baseline
        "detail": detail,
    }))
    print(json.dumps({
        "metric": "serve_p50_ms",
        "value": round(cont["p50_ms"], 1),
        "unit": "ms request latency, p50 (lower is better)",
        "vs_baseline": round(seq["p50_ms"] / cont["p50_ms"], 3),
        "detail": {"sequential_p50_ms": round(seq["p50_ms"], 1)},
    }))
    print(json.dumps({
        "metric": "serve_p99_ms",
        "value": round(cont["p99_ms"], 1),
        "unit": "ms request latency, p99 (lower is better)",
        "vs_baseline": round(seq["p99_ms"] / cont["p99_ms"], 3),
        "detail": {"sequential_p99_ms": round(seq["p99_ms"], 1)},
    }))

    # ---- paged vs dense at EQUAL HBM (ISSUE-11; docs/serving.md "Paged
    # KV & prefix caching"). The dense layout's admission ceiling is its
    # lane count (slots × max_seq tokens of KV reserved up front); the
    # paged pool holds the SAME token capacity but decouples concurrency
    # from it — admission charges each request's real block need, and
    # prefix caching (on by default in the shipped config) additionally
    # shares the burst's common system prompt. Three legs over ONE
    # fleet-shaped burst (shared 48-token system prompt + mixed-length
    # unique tails): dense → paged(prefix off) → paged(prefix on), so
    # the packing win and the prefix win decompose cleanly. Each leg
    # runs twice and keeps its best pass (order-debias: this host's
    # first-leg timings run cold).
    dense_slots = 4
    ab_block_size = 8
    equal_blocks = dense_slots * max_seq // ab_block_size
    # Table rows are host-side (no HBM), so paged slots can exceed the
    # dense lane count freely; 12 ≈ the pool's effective concurrency on
    # this burst — more lanes would pad the decode batch past what
    # admission can fill.
    paged_slots = 12
    ab_buckets = [32, 64, 96]
    ab_max_new = 16

    def run_ab(attention_impl, n_slots, prefix_cache, burst):
        engine = ServingEngine(
            loaded, cfg, slots=n_slots, max_seq_len=max_seq,
            prefill_buckets=ab_buckets, attention_impl=attention_impl,
            kv_block_size=ab_block_size,
            kv_num_blocks=(equal_blocks if attention_impl != "dense"
                           else None))
        bm = BlockManager(
            num_blocks=equal_blocks, block_size=ab_block_size,
            prefix_cache=prefix_cache)
        batcher = ContinuousBatcher(
            engine, queue=AdmissionQueue(len(burst)), block_manager=bm,
            idle_wait_s=0.002)
        batcher.start()
        try:
            t0 = time.time()
            reqs = [batcher.submit(Request(p, max_new_tokens=ab_max_new))
                    for p in burst]
            results = [r.result(timeout=1800) for r in reqs]
            wall = time.time() - t0
            lats = sorted(r["latency_ms"] for r in results)
            stats = batcher.stats()
            return {
                "tokens_per_s": stats["generated_tokens"] / wall,
                "p50_ms": lats[len(lats) // 2],
                "p99_ms": lats[min(len(lats) - 1, int(len(lats) * 0.99))],
                "max_occupancy": stats["max_occupancy"],
                "mean_occupancy": stats["mean_occupancy"],
                "kv": stats["kv_blocks"],
                "hbm_bytes": engine.cache_hbm_bytes(),
            }
        finally:
            batcher.stop()

    def best_of(n, *args):
        runs = [run_ab(*args) for _ in range(n)]
        return max(runs, key=lambda r: r["tokens_per_s"])

    rng2 = np.random.default_rng(1)
    sys_prompt = rng2.integers(1, cfg.vocab_size, size=48).astype(np.int32)
    shared_burst = [
        np.concatenate([sys_prompt,
                        rng2.integers(1, cfg.vocab_size,
                                      size=int(rng2.integers(8, 33)))
                        .astype(np.int32)])
        for _ in range(n_requests)
    ]
    dense_ab = best_of(2, "dense", dense_slots, False, shared_burst)
    pfx_off = best_of(2, "auto", paged_slots, False, shared_burst)
    pfx_on = best_of(2, "auto", paged_slots, True, shared_burst)
    conc_ratio = pfx_on["max_occupancy"] / max(1, dense_ab["max_occupancy"])
    print(json.dumps({
        "metric": "serve_paged_tokens_per_s",
        "value": round(pfx_on["tokens_per_s"], 1),
        "unit": f"tokens/s, shipped paged config vs dense at equal KV HBM "
                f"({equal_blocks}x{ab_block_size}-token blocks vs "
                f"{dense_slots}x{max_seq} lanes; 48-token shared prompt + "
                f"8-32 unique, {ab_max_new} new)",
        "vs_baseline": round(
            pfx_on["tokens_per_s"] / dense_ab["tokens_per_s"], 3),
        "detail": {
            "dense_tokens_per_s": round(dense_ab["tokens_per_s"], 1),
            "paged_prefix_off_tokens_per_s": round(
                pfx_off["tokens_per_s"], 1),
            "dense_p50_ms": round(dense_ab["p50_ms"], 1),
            "dense_p99_ms": round(dense_ab["p99_ms"], 1),
            "paged_p50_ms": round(pfx_on["p50_ms"], 1),
            "paged_p99_ms": round(pfx_on["p99_ms"], 1),
            "dense_hbm_bytes": dense_ab["hbm_bytes"],
            "paged_hbm_bytes": pfx_on["hbm_bytes"],
        },
    }))
    print(json.dumps({
        "metric": "serve_paged_admitted_concurrency",
        "value": pfx_on["max_occupancy"],
        "unit": "peak concurrent sequences on the burst "
                "(equal HBM; gate >= 2x dense)",
        "vs_baseline": round(conc_ratio, 3),
        "detail": {
            "dense_max_occupancy": dense_ab["max_occupancy"],
            "paged_mean_occupancy": pfx_on["mean_occupancy"],
            "dense_mean_occupancy": dense_ab["mean_occupancy"],
        },
    }))
    print(json.dumps({
        "metric": "serve_prefix_cache_tokens_per_s",
        "value": round(pfx_on["tokens_per_s"], 1),
        "unit": "tokens/s on the shared-system-prompt burst "
                "(prefix cache on vs off, both paged)",
        "vs_baseline": round(
            pfx_on["tokens_per_s"] / pfx_off["tokens_per_s"], 3),
        "detail": {
            "off_tokens_per_s": round(pfx_off["tokens_per_s"], 1),
            "prefix_cache_hit_rate": pfx_on["kv"]["prefix_cache_hit_rate"],
            "prefix_hit_tokens": pfx_on["kv"]["prefix_hit_tokens"],
            "on_blocks_allocated": pfx_on["kv"]["total_allocated"],
            "off_blocks_allocated": pfx_off["kv"]["total_allocated"],
            "on_p99_ms": round(pfx_on["p99_ms"], 1),
            "off_p99_ms": round(pfx_off["p99_ms"], 1),
        },
    }))

    # ---- request tracing on/off A/B (ISSUE-12; docs/serving.md "Request
    # latency & SLOs"). Same burst through the 8-slot batcher with the
    # RequestTracer attached (sample=1.0, shipper thread live) vs without;
    # interleaved best-of-2 per arm debiases cache warmth. Gate: tracing
    # costs < 1% tokens/s — span trees are retire-time buffer appends, so
    # steady-state decode executes zero tracing code. The traced arm also
    # yields the TTFT/TPOT/e2e histograms recorded in BENCH.md.
    t_off = [run(slots, tracing=False)]
    t_on = [run(slots, tracing=True)]
    t_off.append(run(slots, tracing=False))
    t_on.append(run(slots, tracing=True))
    best_off = max(t_off, key=lambda r: r["tokens_per_s"])
    best_on = max(t_on, key=lambda r: r["tokens_per_s"])
    overhead_pct = (1.0 - best_on["tokens_per_s"]
                    / best_off["tokens_per_s"]) * 100.0
    lat = best_on["latency"]
    print(json.dumps({
        "metric": "serve_trace_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "% tokens/s lost with request tracing on "
                "(gate < 1%; negative = within noise)",
        "vs_baseline": round(
            best_on["tokens_per_s"] / best_off["tokens_per_s"], 4),
        "detail": {
            "gate_passed": overhead_pct < 1.0,
            "on_tokens_per_s": round(best_on["tokens_per_s"], 1),
            "off_tokens_per_s": round(best_off["tokens_per_s"], 1),
            "spans_recorded": best_on["spans_recorded"],
            "ttft_p50_ms": lat["ttft"]["p50_ms"],
            "ttft_p99_ms": lat["ttft"]["p99_ms"],
            "tpot_p50_ms": lat["tpot"]["p50_ms"],
            "tpot_p99_ms": lat["tpot"]["p99_ms"],
            "e2e_p50_ms": lat["e2e"]["p50_ms"],
            "e2e_p99_ms": lat["e2e"]["p99_ms"],
            "queue_wait_p99_ms": lat["queue_wait"]["p99_ms"],
        },
    }))


def serve_fleet_bench() -> None:
    """`make bench-serve-fleet` (docs/serving.md "Deployments &
    autoscaling"): fleet serving through the REAL master router.

    Measures the FLEET TIER — deployment controller + /serve router — on
    a 2-agent devcluster: the SAME client burst runs against target=1 and
    target=2 of one deployment, gating 2-replica routed throughput >=
    1.8x single-replica, then a rolling drain (scale 2 -> 1 mid-burst)
    gates ZERO dropped accepted requests.

    The replicas are slot-capacity-bound with a FIXED per-request service
    time (tests/fixtures/serving/fake_replica.py, the same protocol as
    the real serve task): in production each replica owns its own TPU, so
    per-replica capacity is slots x service-time and replicas scale
    independently. Running two REAL engines on this bench host's shared
    CPU would measure core contention, not the router — `make
    bench-serve` already gates the real single-engine batcher on real
    tokens.
    """
    import os
    import subprocess
    import tempfile
    import threading
    import urllib.request

    REPO = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                   check=True, capture_output=True)
    import sys as _sys

    if os.path.join(REPO, "tests") not in _sys.path:
        _sys.path.insert(0, os.path.join(REPO, "tests"))
    from tests.test_platform_e2e import Devcluster

    tmp = tempfile.mkdtemp(prefix="bench_serve_fleet_")
    # 4 slots x 250ms service time per replica = 16 req/s of per-replica
    # capacity, far above the ~10ms/request of Python/HTTP plumbing even
    # on a 1-core bench host — so capacity binds, not host CPU. 16
    # clients oversubscribe one replica ~4x; the only way to 1.8x is the
    # router actually spreading load over replica 2.
    gen_ms = 250
    config = {
        "name": "bench-fleet",
        "entrypoint": "python3 -m tests.fixtures.serving.fake_replica",
        "serving": {
            "model": "gpt2",
            "heartbeat_period_s": 0.3,
            # Autoscaling quiesced (threshold above the signal's ceiling):
            # this bench A/Bs replica counts MANUALLY — the burst's
            # backpressure would otherwise scale the "single" phase up
            # mid-measurement (the autoscaler doing its job).
            "replicas": {"min": 1, "max": 2, "target": 1,
                         "scale_up_threshold": 2.0,
                         "scale_up_after_s": 3600},
        },
        "resources": {"slots_per_trial": 0},
        "environment": {
            "DET_FAKE_GEN_MS": str(gen_ms),
            "DET_FAKE_SLOTS": "4",
            "DET_FAKE_HEARTBEAT_S": "0.3",
        },
    }

    n_requests, max_new, n_clients = 96, 16, 16

    cluster = Devcluster(tmp, os.path.join(REPO, "native", "bin"), slots=1)
    try:
        cluster.start_master()
        cluster.start_agent("fleet-a")
        cluster.start_agent("fleet-b")
        token = cluster.login()
        dep_id = cluster.api("POST", "/api/v1/deployments",
                             {"config": config}, token=token)["id"]

        def _detail():
            return cluster.api("GET", f"/api/v1/deployments/{dep_id}",
                               token=token)["deployment"]

        def _wait_ready(n, timeout=300.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                d = _detail()
                ready = [r for r in d["replicas"]
                         if r.get("allocation_state") == "RUNNING"
                         and r.get("proxy_address") and not r["retiring"]
                         and 0 <= (r.get("report_age_s") or -1) < 10]
                if len(ready) == n and len(d["replicas"]) == n:
                    return d
                time.sleep(0.3)
            raise TimeoutError(f"never reached {n} ready replicas: {d}")

        def _generate(timeout=120.0):
            req = urllib.request.Request(
                f"{cluster.master_url}/serve/{dep_id}/v1/generate",
                data=json.dumps({"tokens": [5, 9, 17, 3],
                                 "max_new_tokens": max_new,
                                 "delay_ms": gen_ms,
                                 "timeout_s": timeout}).encode(),
                headers={"Content-Type": "application/json",
                         "Authorization": f"Bearer {token}"},
                method="POST")
            with urllib.request.urlopen(req, timeout=timeout + 30) as resp:
                return json.loads(resp.read())

        def burst():
            """n_requests through the router from n_clients threads;
            returns (tokens_per_s, completed, dropped)."""
            done, errors = [], []
            counter = iter(range(n_requests))
            lock = threading.Lock()

            def _client():
                import urllib.error

                while True:
                    with lock:
                        if next(counter, None) is None:
                            return
                    deadline = time.time() + 300
                    while True:
                        try:
                            out = _generate()
                            if len(out.get("tokens", [])) == max_new:
                                done.append(out)
                            else:
                                errors.append(out)
                            break
                        except urllib.error.HTTPError as e:
                            if e.code in (429, 503) and \
                                    time.time() < deadline:
                                # Backpressure, not a drop: honor the
                                # Retry-After hint like the harness
                                # Session does.
                                ra = e.headers.get("Retry-After")
                                time.sleep(min(float(ra or 1), 5.0))
                                continue
                            errors.append(f"HTTP {e.code}")
                            break
                        except Exception as e:  # noqa: BLE001
                            errors.append(str(e)[:200])
                            break

            t0 = time.time()
            threads = [threading.Thread(target=_client)
                       for _ in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            wall = time.time() - t0
            return len(done) * max_new / wall, len(done), errors

        _wait_ready(1)
        burst()  # warm both the replica and the router once, untimed
        single_tps, single_done, single_err = burst()

        cluster.api("POST", f"/api/v1/deployments/{dep_id}/scale",
                    {"target": 2}, token=token)
        _wait_ready(2)
        fleet_tps, fleet_done, fleet_err = burst()

        # Rolling drain under load: scale 2 -> 1 mid-burst; every accepted
        # request must complete (zero dropped).
        drain_result = {}

        def _drain_burst():
            drain_result["r"] = burst()

        loader = threading.Thread(target=_drain_burst)
        loader.start()
        time.sleep(0.5)
        cluster.api("POST", f"/api/v1/deployments/{dep_id}/scale",
                    {"target": 1}, token=token)
        loader.join(timeout=600)
        _, drain_done, drain_err = drain_result["r"]
        deadline = time.time() + 120
        while time.time() < deadline:
            if len(_detail()["replicas"]) == 1:
                break
            time.sleep(0.5)
    finally:
        cluster.stop()

    speedup = fleet_tps / single_tps if single_tps else 0.0
    detail = {
        "replica": f"4 slots x {gen_ms}ms service time (fleet-tier bench; "
                   "see docstring)",
        "requests": n_requests,
        "max_new_tokens": max_new,
        "clients": n_clients,
        "single_tokens_per_s": round(single_tps, 1),
        "single_completed": single_done,
        "fleet_completed": fleet_done,
        "errors": [single_err, fleet_err][:2],
        "drain_completed": drain_done,
        "drain_dropped": len(drain_err),
    }
    print(json.dumps({
        "metric": "serve_fleet_tokens_per_s",
        "value": round(fleet_tps, 1),
        "unit": f"tokens/s routed through /serve (2 replicas, "
                f"{n_requests}-burst x {max_new} new tokens)",
        "vs_baseline": round(speedup, 3),  # single replica IS the baseline
        "detail": detail,
    }))
    print(json.dumps({
        "metric": "serve_fleet_drain_dropped",
        "value": len(drain_err),
        "unit": "requests dropped during a rolling drain under load "
                "(gate: 0)",
        "detail": {"drain_completed": drain_done,
                   "drain_errors": drain_err[:5]},
    }))
    assert not single_err and not fleet_err, (single_err, fleet_err)
    assert len(drain_err) == 0, f"rolling drain dropped: {drain_err[:5]}"
    assert speedup >= 1.8, (
        f"2-replica routed throughput only {speedup:.2f}x single replica "
        f"(gate: 1.8x; {detail})")


def lifecycle_bench() -> None:
    """`make bench-lifecycle` (docs/serving.md "Model lifecycle"): the
    train→serve delivery loop under load on the REAL master.

    Phase 1 — **rolling weight swap under sustained load**: a 2-replica
    deployment serves a continuous client burst while `update` rolls it
    from version 1 to version 2 (spawn-at-new before drain-at-old).
    Gate: ZERO dropped accepted requests, and the deployment ends with
    every replica at v2.

    Phase 2 — **canary fraction fidelity**: a 10% canary on version 3
    takes a counted 200-request burst; the router's deterministic debt
    split must put the OBSERVED canary fraction within ±5 points of the
    configured 0.10 (the acceptance gate), with canary-vs-stable p50/p99
    reported from the per-version latency aggregation.

    Replicas are the fake-replica fixture (slot-capacity-bound, fixed
    service time) for the same reason as bench-serve-fleet: the subsystem
    under test is the master's lifecycle controller + router, and `make
    bench-serve` already gates the real engine.
    """
    import os
    import subprocess
    import tempfile
    import threading
    import urllib.request

    REPO = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                   check=True, capture_output=True)
    import sys as _sys

    if os.path.join(REPO, "tests") not in _sys.path:
        _sys.path.insert(0, os.path.join(REPO, "tests"))
    from tests.test_platform_e2e import Devcluster

    tmp = tempfile.mkdtemp(prefix="bench_lifecycle_")
    gen_ms = 100
    config = {
        "name": "bench-lifecycle",
        "entrypoint": "python3 -m tests.fixtures.serving.fake_replica",
        "serving": {
            "model": "gpt2",
            "model_version": "bench:1",
            "heartbeat_period_s": 0.3,
            # Autoscaling quiesced: replica counts move only through the
            # lifecycle verbs under measurement.
            "replicas": {"min": 1, "max": 4, "target": 2,
                         "scale_up_threshold": 2.0,
                         "scale_up_after_s": 3600},
        },
        "resources": {"slots_per_trial": 0},
        "environment": {
            "DET_FAKE_GEN_MS": str(gen_ms),
            "DET_FAKE_SLOTS": "4",
            "DET_FAKE_HEARTBEAT_S": "0.3",
        },
    }
    canary_fraction, canary_n = 0.10, 200

    cluster = Devcluster(tmp, os.path.join(REPO, "native", "bin"), slots=1)
    try:
        cluster.start_master()
        cluster.start_agent("lc-a")
        cluster.start_agent("lc-b")
        token = cluster.login()
        # Registry: three committed versions of model `bench`.
        cluster.api("POST", "/api/v1/models",
                    {"name": "bench", "metadata": {}, "labels": []},
                    token=token)
        for uuid in ("bench-ck-1", "bench-ck-2", "bench-ck-3"):
            cluster.api("POST", "/api/v1/checkpoints",
                        {"uuid": uuid, "state": "COMPLETED"}, token=token)
            cluster.api("POST", "/api/v1/models/bench/versions",
                        {"checkpoint_uuid": uuid}, token=token)
        dep_id = cluster.api("POST", "/api/v1/deployments",
                             {"config": config}, token=token)["id"]

        def _detail():
            return cluster.api("GET", f"/api/v1/deployments/{dep_id}",
                               token=token)["deployment"]

        def _wait(pred, timeout=300.0, what="condition"):
            deadline = time.time() + timeout
            while time.time() < deadline:
                d = _detail()
                if pred(d):
                    return d
                time.sleep(0.3)
            raise TimeoutError(f"never reached {what}: {d}")

        def _ready(d, n):
            live = [r for r in d["replicas"]
                    if r.get("allocation_state") == "RUNNING"
                    and r.get("proxy_address") and not r["retiring"]
                    and 0 <= (r.get("report_age_s") or -1) < 10]
            return len(live) >= n

        def _generate(timeout=120.0):
            req = urllib.request.Request(
                f"{cluster.master_url}/serve/{dep_id}/v1/generate",
                data=json.dumps({"tokens": [5, 9, 17, 3],
                                 "max_new_tokens": 8,
                                 "delay_ms": gen_ms,
                                 "timeout_s": timeout}).encode(),
                headers={"Content-Type": "application/json",
                         "Authorization": f"Bearer {token}"},
                method="POST")
            with urllib.request.urlopen(req, timeout=timeout + 30) as resp:
                return json.loads(resp.read())

        _wait(lambda d: _ready(d, 2), what="2 ready replicas")

        # --- Phase 1: rolling swap under sustained load ---------------
        stop_load = threading.Event()
        done, errors = [], []

        def _loader():
            import urllib.error

            while not stop_load.is_set():
                try:
                    out = _generate()
                    done.append(out.get("model_version", ""))
                except urllib.error.HTTPError as e:
                    if e.code in (429, 503):
                        ra = e.headers.get("Retry-After")
                        time.sleep(min(float(ra or 1), 5.0))
                        continue
                    errors.append(f"HTTP {e.code}")
                except Exception as e:  # noqa: BLE001
                    errors.append(str(e)[:200])

        threads = [threading.Thread(target=_loader) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(2.0)  # load established on v1
        t_swap = time.time()
        cluster.api("POST", f"/api/v1/deployments/{dep_id}/update",
                    {"model": "bench", "version": 2}, token=token)
        d = _wait(
            lambda d: (len(d["replicas"]) == 2 and "swap" not in d
                       and all(r["model_version"] == "bench:2"
                               for r in d["replicas"])),
            what="swap complete")
        swap_s = time.time() - t_swap
        time.sleep(2.0)  # load continues on v2
        stop_load.set()
        for t in threads:
            t.join(timeout=120)
        served_v1 = sum(1 for v in done if v == "bench:1")
        served_v2 = sum(1 for v in done if v == "bench:2")

        # --- Phase 2: canary fraction fidelity ------------------------
        cluster.api("POST", f"/api/v1/deployments/{dep_id}/canary",
                    {"model": "bench", "version": 3,
                     "fraction": canary_fraction}, token=token)
        _wait(lambda d: any(
            r.get("canary") and r.get("allocation_state") == "RUNNING"
            and r.get("proxy_address")
            and 0 <= (r.get("report_age_s") or -1) < 10
            for r in d["replicas"]), what="canary replica ready")
        canary_hits = 0
        for _ in range(canary_n):
            out = _generate()
            if out.get("model_version") == "bench:3":
                canary_hits += 1
        observed = canary_hits / canary_n
        d = _detail()
        by_version = {}
        for version, lat in (d.get("latency_by_version") or {}).items():
            e2e = lat.get("e2e") or {}
            by_version[version] = {
                "p50_ms": e2e.get("p50_ms"), "p99_ms": e2e.get("p99_ms"),
                "requests": e2e.get("count")}
        cluster.api("POST", f"/api/v1/deployments/{dep_id}/canary",
                    {"abort": True}, token=token)
    finally:
        cluster.stop()

    detail = {
        "replica": f"4 slots x {gen_ms}ms service time (controller bench; "
                   "see docstring)",
        "swap_seconds": round(swap_s, 2),
        "swap_served_v1": served_v1,
        "swap_served_v2": served_v2,
        "swap_errors": errors[:5],
        "canary_requests": canary_n,
        "canary_hits": canary_hits,
        "latency_by_version_ms": by_version,
    }
    print(json.dumps({
        "metric": "lifecycle_swap_dropped",
        "value": len(errors),
        "unit": "requests dropped during a rolling weight swap under "
                "sustained load (gate: 0)",
        "detail": detail,
    }))
    print(json.dumps({
        "metric": "lifecycle_canary_observed_fraction",
        "value": round(observed, 3),
        "unit": f"observed canary traffic fraction over {canary_n} "
                f"requests (configured {canary_fraction}; gate: within "
                "±0.05)",
        "detail": {"by_version": by_version},
    }))
    assert len(errors) == 0, f"rolling swap dropped: {errors[:5]}"
    assert served_v1 > 0 and served_v2 > 0, detail
    assert abs(observed - canary_fraction) <= 0.05, (
        f"canary observed {observed:.3f} vs configured {canary_fraction} "
        f"(gate ±0.05; {detail})")


def capacity_bench() -> None:
    """`make bench-capacity` (docs/cluster-ops.md "Capacity loop"): the
    closed capacity loop under a diurnal traffic replay.

    One elastic fleet — master + GCP-shaped fake TPU API — where serving
    demand drives MACHINES: ramp up (autoscaler raises replica target →
    replica deficits summon nodes → this bench "boots" each created node
    as a real agent, spot-tiered), plateau, a SPOT-KILL wave (preemption
    notices on every spot agent + out-of-band node delete; replicas drain
    inside the deadline while replacements re-target on-demand), ramp
    down, idle (scale-to-zero drains the last replica, idle nodes are
    deleted — the fleet returns to zero), then a COLD-START burst (the
    router wakes target 0 -> 1, holds the first request within
    cold_start_budget_s, and its trace shows serve.cold_start with
    engine_source=deserialize — the warm-AOT path, never a re-trace).

    Gates: node count demonstrably rises and falls with the replayed
    demand, >= 1 spot agent drains inside its notice deadline, the
    scale-to-zero -> cold-start cycle completes within the budget on the
    warm AOT path, and dropped accepted requests == 0 across the whole
    replay (429/503-with-Retry-After shedding is backpressure, not a
    drop; anything else is)."""
    import os
    import subprocess
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    REPO = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                   check=True, capture_output=True)
    import sys as _sys

    for p in (REPO, os.path.join(REPO, "tests")):
        if p not in _sys.path:
            _sys.path.insert(0, p)
    from tests.test_platform_e2e import Devcluster, _wait_http
    from tests.test_provisioner import FakeTpuApi

    tmp = tempfile.mkdtemp(prefix="bench_capacity_")
    fake = FakeTpuApi()
    cold_budget = 60.0
    master_cfg = {
        "agent_timeout_s": 15,
        "provisioner": {
            "type": "gcp",
            "api_base": fake.url + "/v2",
            "project": "p", "zone": "z",
            "slots_per_node": 1,
            "sustain_seconds": 0.4,
            "cooldown_seconds": 0.8,
            "idle_seconds": 3,
            "reconcile_seconds": 0.3,
            "demand_hysteresis_seconds": 2,
            "spot": True,
        },
    }
    gen_ms = 200
    dep_cfg = {
        "name": "diurnal",
        "entrypoint": "python3 -m tests.fixtures.serving.fake_replica",
        "serving": {
            "model": "gpt2",
            "heartbeat_period_s": 0.3,
            "replicas": {
                "min": 0, "max": 4, "target": 1,
                "on_demand_floor": 1,
                "cold_start_budget_s": cold_budget,
                "scale_up_after_s": 1.0,
                "scale_down_after_s": 2.5,
                "scale_up_threshold": 0.5,
                "scale_down_threshold": 0.1,
            },
        },
        "resources": {"slots": 1},
        "environment": {
            "DET_FAKE_GEN_MS": str(gen_ms),
            "DET_FAKE_SLOTS": "2",
            "DET_FAKE_HEARTBEAT_S": "0.3",
        },
    }

    cluster = Devcluster(tmp, os.path.join(REPO, "native", "bin"), slots=1)
    cfg_path = os.path.join(tmp, "master.json")
    with open(cfg_path, "w") as f:
        json.dump(master_cfg, f)
    cluster.master = subprocess.Popen(
        [os.path.join(cluster.binaries, "determined-master"),
         "--config", cfg_path, "--port", str(cluster.port),
         "--host", "127.0.0.1", "--db", cluster.db_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    _wait_http(cluster.master_url + "/api/v1/master")

    agents = {}          # node name -> Popen
    node_counts = []     # (t, tracked agents alive) samples
    dropped = []         # non-backpressure request failures
    completed = [0]
    stop_all = threading.Event()
    token = cluster.login()
    admin = cluster.login("admin")

    def boot_watcher():
        """Play the cloud: every node the provisioner creates 'boots' as
        a real agent a moment later. Every SECOND node is spot-tiered
        (preemptible), so the deployment floor has on-demand capacity to
        live on and the surplus has spot to be reclaimed from."""
        while not stop_all.is_set():
            for i, create in enumerate(list(fake.creates)):
                name = create["name"]
                if name in agents or name not in fake.node_names():
                    continue
                spot = i % 2 == 1
                env = dict(cluster.env)
                if spot:
                    env["DET_AGENT_PREEMPTIBLE"] = "1"
                agents[name] = subprocess.Popen(
                    [os.path.join(cluster.binaries, "determined-agent"),
                     "--master-url", cluster.master_url, "--id", name,
                     "--slots", "1", "--slot-type", "cpu",
                     "--addr", "127.0.0.1",
                     "--work-root", os.path.join(tmp, f"agent-{name}"),
                     "--token-file", cluster.db_path + ".agent_token"],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT)
            time.sleep(0.2)

    def sample_nodes():
        while not stop_all.is_set():
            node_counts.append((time.time(), len(fake.node_names())))
            time.sleep(0.5)

    def one_request(timeout=cold_budget + 30):
        req = urllib.request.Request(
            f"{cluster.master_url}/serve/diurnal/v1/generate",
            data=json.dumps({"tokens": [5, 9, 17],
                             "max_new_tokens": 8,
                             "delay_ms": gen_ms}).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {token}"},
            method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
            return resp.headers.get("X-Request-Id"), out

    def client_loop(rate_hz):
        """Closed-loop client at ~rate_hz; 429/503 honor Retry-After
        (backpressure), anything else counts as a DROP."""
        deadline_absent = object()
        while not stop_all.is_set() and rate_hz[0] > 0:
            t0 = time.time()
            try:
                one_request(timeout=30)
                completed[0] += 1
            except urllib.error.HTTPError as e:
                if e.code in (429, 503):
                    ra = e.headers.get("Retry-After", deadline_absent)
                    if ra is deadline_absent:
                        dropped.append(f"{e.code} without Retry-After")
                    else:
                        time.sleep(min(float(ra), 3.0))
                else:
                    dropped.append(f"HTTP {e.code}")
            except Exception as e:  # noqa: BLE001
                dropped.append(str(e)[:160])
            sleep = 1.0 / max(rate_hz[0], 0.1) - (time.time() - t0)
            if sleep > 0:
                time.sleep(sleep)

    threading.Thread(target=boot_watcher, daemon=True).start()
    threading.Thread(target=sample_nodes, daemon=True).start()

    phase_log = []
    spot_drained_in_deadline = False
    cold = {}
    try:
        dep = cluster.api("POST", "/api/v1/deployments",
                          {"config": dep_cfg}, token=token)
        assert dep["id"]

        def detail():
            return cluster.api("GET", f"/api/v1/deployments/{dep['id']}",
                               token=token)["deployment"]

        def live_replicas(d=None):
            d = d or detail()
            return [r for r in d["replicas"]
                    if not r["retiring"]
                    and r.get("allocation_state") == "RUNNING"
                    and r.get("proxy_address")]

        def wait_for(cond, timeout, what):
            deadline = time.time() + timeout
            while time.time() < deadline:
                v = cond()
                if v:
                    return v
                time.sleep(0.3)
            raise TimeoutError(f"capacity replay: {what}")

        # --- ramp up -------------------------------------------------
        phase_log.append(("ramp_up", time.time()))
        wait_for(lambda: live_replicas() or None, 90,
                 "first replica never came up")
        rate = [2.0]
        clients = [threading.Thread(target=client_loop, args=(rate,),
                                    daemon=True) for _ in range(8)]
        for c in clients:
            c.start()
        # Backpressure raises the target; deficits summon nodes.
        wait_for(lambda: len(live_replicas()) >= 3 or None, 120,
                 "autoscaler never grew the fleet under load")
        peak_nodes = len(fake.node_names())

        # --- plateau -------------------------------------------------
        phase_log.append(("plateau", time.time()))
        time.sleep(5)

        # --- spot-kill wave -----------------------------------------
        phase_log.append(("spot_kill", time.time()))
        spot_agents = [a["id"] for a in cluster.api(
            "GET", "/api/v1/agents", token=token)["agents"]
            if a["preemptible"] and a["alive"]]
        assert spot_agents, "replay never placed capacity on spot"
        kill_deadline_s = 20.0
        t_notice = time.time()
        for aid in spot_agents:
            cluster.api("POST", f"/api/v1/agents/{aid}/preempt_notice",
                        {"deadline_seconds": kill_deadline_s,
                         "reason": "spot_preemption"}, token=admin)

        def spot_drained():
            d = detail()
            draining = [r for r in d["replicas"]
                        if r.get("agent") in spot_agents
                        and r.get("allocation_state") == "RUNNING"]
            return not draining or None

        wait_for(spot_drained, kill_deadline_s + 10,
                 "spot replicas never finished draining")
        spot_drained_in_deadline = \
            time.time() - t_notice <= kill_deadline_s + 5
        # The nodes actually vanish (the cloud reclaims them).
        for aid in spot_agents:
            fake.interrupt(aid)
            p = agents.get(aid)
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
        # Service continues on on-demand capacity.
        wait_for(lambda: live_replicas() or None, 60,
                 "no live replica after the spot wave")

        # --- ramp down → idle → scale-to-zero ------------------------
        phase_log.append(("ramp_down", time.time()))
        rate[0] = 0
        stop_all_clients = time.time()
        for c in clients:
            c.join(timeout=40)

        def fleet_zero():
            d = detail()
            return (int(d["target_replicas"]) == 0 and not d["replicas"]
                    and not fake.node_names()) or None

        wait_for(fleet_zero, 150,
                 "fleet never scaled to zero (replicas + nodes)")
        phase_log.append(("zero", time.time()))
        trough_nodes = len(fake.node_names())

        # --- cold-start burst ---------------------------------------
        phase_log.append(("cold_burst", time.time()))
        t_cold = time.time()
        rid, out = one_request()   # held through the wake, never shed
        cold_wall_s = time.time() - t_cold
        completed[0] += 1
        trace = cluster.api(
            "GET",
            f"/api/v1/deployments/{dep['id']}/requests/{rid}/trace",
            token=token)
        spans = {s["name"]: s for s in trace["spans"]}
        cold_span = spans.get("serve.cold_start")
        cold = {
            "wall_s": round(cold_wall_s, 2),
            "within_budget": cold_wall_s <= cold_budget,
            "span_present": cold_span is not None,
            "engine_source": (cold_span or {}).get(
                "attrs", {}).get("engine_source"),
        }
        # A few follow-ups ride the now-warm deployment.
        for _ in range(4):
            one_request(timeout=30)
            completed[0] += 1
    finally:
        stop_all.set()
        for p in agents.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        cluster.stop()
        fake.stop()

    counts = [n for _, n in node_counts]
    detail_out = {
        "phases": [(name, round(t - phase_log[0][1], 1))
                   for name, t in phase_log],
        "node_count_peak": max(counts) if counts else 0,
        "node_count_final": trough_nodes,
        "nodes_created_total": len(fake.creates),
        "completed_requests": completed[0],
        "dropped": dropped[:10],
        "spot_agents_killed": len(spot_agents),
        "spot_drained_in_deadline": spot_drained_in_deadline,
        "cold_start": cold,
        "idle_window_s": round(time.time() - stop_all_clients, 1),
    }
    print(json.dumps({
        "metric": "capacity_diurnal_dropped",
        "value": len(dropped),
        "unit": "accepted requests dropped across the replay (gate: 0)",
        "detail": detail_out,
    }))
    print(json.dumps({
        "metric": "capacity_cold_start_s",
        "value": cold.get("wall_s"),
        "unit": f"scale-from-zero wake to first response "
                f"(gate: <= {cold_budget}s, warm AOT)",
        "detail": cold,
    }))
    assert max(counts) >= 3, f"fleet never grew: peak={max(counts)}"
    assert trough_nodes == 0, "fleet never shrank back to zero nodes"
    assert spot_drained_in_deadline, "spot wave missed its drain deadline"
    assert not dropped, f"dropped accepted requests: {dropped[:5]}"
    assert cold["within_budget"] and cold["span_present"], cold
    assert cold["engine_source"] == "deserialize", cold
    assert peak_nodes >= 2


def pp_compile_check() -> None:
    """AOT-compile the bf16 pipeline-parallel train step against a v5e 2x2
    TPU topology (deviceless — works with the single bench chip).

    Why: on the CPU backend the bf16 partial-manual shard_map gradient trips
    an XLA partitioner crash, so CPU tests run the PP path in f32
    (models/gpt2.py apply_pipelined). This check runs the REAL TPU
    partitioner over the bf16 graph, closing that blind spot without
    needing 8 physical chips.
    """
    import jax
    import numpy as np
    import optax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from determined_tpu.models import gpt2
    from determined_tpu.parallel.mesh import AXIS_ORDER, MeshConfig
    from determined_tpu.train import create_train_state, make_train_step

    topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2")
    shape = MeshConfig(data=2, pipeline=2).resolve(len(topo.devices)).sizes()
    mesh = Mesh(np.asarray(topo.devices).reshape(shape), AXIS_ORDER)

    cfg = gpt2.Config.tiny()
    assert cfg.dtype == jax.numpy.bfloat16
    # apply_pipelined picks its compute dtype from the DEFAULT backend — on
    # a cpu default it would compile the f32 graph and this check would be
    # a false green (the whole point is bf16 on the TPU partitioner).
    assert jax.default_backend() in ("tpu", "axon"), (
        f"pp-compile-check needs a TPU default backend, got "
        f"{jax.default_backend()}"
    )
    tx = optax.adamw(3e-4)

    def loss(p, b, r):
        return gpt2.loss_fn_pipelined(p, b, cfg, mesh, num_microbatches=4)

    step = make_train_step(loss, tx, mesh=mesh)
    key = jax.random.PRNGKey(0)
    # Ambient mesh must be the ABSTRACT one: a concrete topology mesh would
    # route eager ops at devices this host doesn't have.
    with jax.sharding.use_abstract_mesh(mesh.abstract_mesh):
        state = jax.eval_shape(
            lambda r: create_train_state(lambda rr: gpt2.init(rr, cfg), tx, r),
            key,
        )
    repl = NamedSharding(mesh, PartitionSpec())
    state = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=repl)
        if hasattr(x, "shape") else x,
        state,
    )
    batch = {
        "tokens": jax.ShapeDtypeStruct(
            (8, 17), np.int32,
            sharding=NamedSharding(mesh, PartitionSpec(("data", "fsdp"))),
        )
    }
    rng = jax.ShapeDtypeStruct((2,), np.uint32, sharding=repl)
    with jax.sharding.use_abstract_mesh(mesh.abstract_mesh):
        compiled = jax.jit(step).lower(state, batch, rng).compile()
    print(json.dumps({
        "check": "pp_bf16_tpu_compile",
        "ok": True,
        "topology": "v5e:2x2",
        "mesh": dict(zip(AXIS_ORDER, shape)),
        "flops": compiled.cost_analysis().get("flops", 0),
    }))


def main() -> int:
    only = None
    if "--only" in sys.argv:
        only = sys.argv[sys.argv.index("--only") + 1]
    sections = {
        "gpt2": gpt2_bench,
        "resnet": lambda: __import__("bench_resnet").main(),
        "asha": lambda: __import__("bench_asha").main(),
        "input": input_pipeline_bench,
        "train_attn": train_attn_bench,
        "serve": serve_bench,
        "serve_fleet": serve_fleet_bench,
        "lifecycle": lifecycle_bench,
        "capacity": capacity_bench,
        "elastic": elastic_bench,
        "trace": trace_bench,
        "compile": compile_bench,
    }
    rc = 0
    for name, fn in sections.items():
        if only is not None and name != only:
            continue
        try:
            fn()
            sys.stdout.flush()
        except Exception as e:  # a broken section must not hide the others
            print(json.dumps({"metric": name, "error": str(e)[:500]}))
            rc = 1
    return rc


if __name__ == "__main__":
    if "--pp-compile-check" in sys.argv:
        pp_compile_check()
        sys.exit(0)
    sys.exit(main())
